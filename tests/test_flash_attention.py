"""Pallas flash attention vs dense softmax attention (fwd + grad parity).

Runs in interpreter mode on CPU; the identical kernel compiles on TPU.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ddl_tpu.ops.attention import dense_attention
from ddl_tpu.ops.flash_attention import flash_attention


@pytest.fixture(scope="module")
def qkv():
    rng = np.random.default_rng(0)
    shape = (2, 64, 3, 16)  # (B, T, H, D)
    return tuple(
        jnp.asarray(rng.normal(size=shape), jnp.float32) for _ in range(3)
    )


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("block", [16, 32, 64])
def test_flash_matches_dense_forward(qkv, causal, block):
    q, k, v = qkv
    out = flash_attention(q, k, v, causal=causal, block_q=block, block_k=block)
    want = dense_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(want), atol=2e-5, rtol=1e-4)


@pytest.mark.parametrize("causal", [False, True])
def test_flash_matches_dense_grads(qkv, causal):
    q, k, v = qkv
    rng = np.random.default_rng(1)
    cot = jnp.asarray(rng.normal(size=q.shape), jnp.float32)

    def loss_flash(q, k, v):
        return (flash_attention(q, k, v, causal=causal, block_q=16, block_k=32) * cot).sum()

    def loss_dense(q, k, v):
        return (dense_attention(q, k, v, causal=causal) * cot).sum()

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gd = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for a, b, name in zip(gf, gd, "qkv"):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=3e-5, rtol=1e-4, err_msg=name
        )


def test_flash_mismatched_block_sizes_clamp():
    rng = np.random.default_rng(2)
    q = jnp.asarray(rng.normal(size=(1, 48, 2, 8)), jnp.float32)  # T=48
    out = flash_attention(q, q, q, causal=True)  # blocks clamp the 512 default -> 48
    want = dense_attention(q, q, q, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(want), atol=2e-5, rtol=1e-4)


def test_lm_flash_matches_dense_model():
    """flash=True reproduces the plain model, standalone and with Ulysses."""
    import optax

    from ddl_tpu.models.transformer import LMConfig
    from ddl_tpu.parallel.sharding import LMMeshSpec
    from ddl_tpu.train.lm_steps import make_lm_step_fns

    def run(spec, **cfg_kw):
        cfg = LMConfig(
            vocab_size=32, d_model=32, n_layers=2, n_heads=4, head_dim=8,
            d_ff=64, compute_dtype="float32", remat=False, **cfg_kw,
        )
        fns = make_lm_step_fns(
            cfg, spec, optax.adam(1e-3), jax.random.key(0), 4, 16
        )
        rng = np.random.default_rng(0)
        x = rng.integers(0, 32, (4, 17))
        state, m = fns.train(
            fns.init_state(), jnp.asarray(x[:, :-1]), jnp.asarray(x[:, 1:])
        )
        return float(m["loss"])

    ref = run(LMMeshSpec())
    flash_1dev = run(LMMeshSpec(data=2, model=2), flash=True)
    flash_uly = run(
        LMMeshSpec(data=2, seq=2, model=2), attn_impl="ulysses", flash=True
    )
    np.testing.assert_allclose(ref, flash_1dev, atol=1e-4)
    np.testing.assert_allclose(ref, flash_uly, atol=1e-4)


def test_lm_flash_rejects_bad_combos():
    import optax

    from ddl_tpu.models.transformer import LMConfig
    from ddl_tpu.parallel.sharding import LMMeshSpec
    from ddl_tpu.train.lm_steps import make_lm_step_fns

    base = dict(
        vocab_size=32, d_model=32, n_layers=1, n_heads=4, head_dim=8,
        d_ff=64, compute_dtype="float32", remat=False, flash=True,
    )
    # flash + ring is no longer an error: the per-device blocks run
    # through the kernel (flash inside ring, see
    # test_ring_flash_matches_ring_dense / test_lm_ring_flash_matches_dense)
    with pytest.raises(ValueError, match="ulysses"):
        make_lm_step_fns(
            LMConfig(**base, attn_impl="dense"), LMMeshSpec(seq=2),
            optax.adam(1e-3), jax.random.key(0), 4, 16,
        )


def test_flash_bf16_finite():
    rng = np.random.default_rng(3)
    q = jnp.asarray(rng.normal(size=(1, 32, 2, 8)), jnp.bfloat16)
    out = flash_attention(q, q, q, causal=True, block_q=16, block_k=16)
    assert out.dtype == jnp.bfloat16
    assert bool(jnp.isfinite(out.astype(jnp.float32)).all())


def test_flash_auto_resolution():
    """flash="auto" picks the kernel only past the measured train-step
    crossover (PERF.md: dense wins at T=512, flash from T=1024) and only
    where the composition supports it."""
    import dataclasses

    from ddl_tpu.models.transformer import LMConfig
    from ddl_tpu.parallel.sharding import LMMeshSpec
    from ddl_tpu.train.lm_steps import FLASH_AUTO_MIN_T, resolve_auto_flash

    base = LMConfig(
        vocab_size=32, d_model=32, n_layers=1, n_heads=4, head_dim=8,
        d_ff=64, flash="auto",
    )
    spec = LMMeshSpec()
    assert resolve_auto_flash(base, spec, FLASH_AUTO_MIN_T - 1) is False
    assert resolve_auto_flash(base, spec, FLASH_AUTO_MIN_T) is True
    # ring auto is thresholded on the PER-DEVICE block: flash-in-ring from
    # T_local >= 2048 (device-only kernel crossover), dense blocks below
    ring = dataclasses.replace(base, attn_impl="ring")
    assert resolve_auto_flash(ring, LMMeshSpec(seq=2), 8192) is True
    assert resolve_auto_flash(ring, LMMeshSpec(seq=2), 2048) is False
    assert resolve_auto_flash(ring, LMMeshSpec(seq=4), 8192) is True
    assert resolve_auto_flash(ring, LMMeshSpec(seq=8), 8192) is False
    # degenerate seq=1 ring == full-sequence kernel: the step-level 1024
    # crossover applies, not the per-hop one
    assert resolve_auto_flash(ring, LMMeshSpec(), 1024) is True
    assert resolve_auto_flash(ring, LMMeshSpec(), 512) is False
    # dense attention cannot see a sharded sequence: stays dense
    assert resolve_auto_flash(base, LMMeshSpec(seq=2), 8192) is False
    bidir = dataclasses.replace(base, causal=False)
    assert resolve_auto_flash(bidir, spec, 8192) is False
    # ulysses attends the full sequence per head group: supported
    uly = dataclasses.replace(base, attn_impl="ulysses")
    assert resolve_auto_flash(uly, LMMeshSpec(seq=2), 8192) is True
    # ...but only when the local heads split exactly over 'seq' in the
    # all-to-all; n_heads=4, model=2 leaves 2 local heads, seq=4 doesn't fit
    assert resolve_auto_flash(uly, LMMeshSpec(seq=4, model=2), 8192) is False
    # heads must shard over 'model' for the manual core: fall back to dense
    assert resolve_auto_flash(base, LMMeshSpec(model=3), 8192) is False


def test_flash_rejects_unknown_string():
    import optax

    from ddl_tpu.models.transformer import LMConfig
    from ddl_tpu.parallel.sharding import LMMeshSpec
    from ddl_tpu.train.lm_steps import make_lm_step_fns

    cfg = LMConfig(
        vocab_size=32, d_model=32, n_layers=1, n_heads=4, head_dim=8,
        d_ff=64, compute_dtype="float32", remat=False, flash="off",
    )
    with pytest.raises(ValueError, match="flash must be"):
        make_lm_step_fns(
            cfg, LMMeshSpec(), optax.adam(1e-3), jax.random.key(0), 4, 16
        )


def test_flash_auto_short_seq_trains_dense():
    """auto at short T resolves to the dense path and steps fine — in
    particular the auto+ring composition must resolve instead of hitting
    the flash/ring ValueError."""
    import jax
    import numpy as np
    import optax

    from ddl_tpu.models.transformer import LMConfig
    from ddl_tpu.parallel.sharding import LMMeshSpec
    from ddl_tpu.train.lm_steps import make_lm_step_fns

    for attn, spec in (("dense", LMMeshSpec()), ("ring", LMMeshSpec(seq=2))):
        cfg = LMConfig(
            vocab_size=32, d_model=32, n_layers=1, n_heads=4, head_dim=8,
            d_ff=64, compute_dtype="float32", remat=False,
            attn_impl=attn, flash="auto",
        )
        fns = make_lm_step_fns(
            cfg, spec, optax.adam(1e-3), jax.random.key(0), 4, 16,
        )
        toks = jnp.asarray(
            np.random.default_rng(0).integers(0, 32, (4, 17))
        )
        state, m = fns.train(fns.init_state(), toks[:, :-1], toks[:, 1:])
        assert np.isfinite(float(m["loss"]))


def test_flash_with_lse_matches_dense_logsumexp():
    """flash_attention_with_lse: out == dense attention, lse == the true
    per-row logsumexp of the scaled scores; both differentiable including
    a nonzero lse cotangent (the ring-combination consumption pattern)."""
    from ddl_tpu.ops.flash_attention import flash_attention_with_lse

    rng = np.random.default_rng(5)
    b, t, h, d = 2, 32, 2, 8
    q, k, v = (
        jnp.asarray(rng.normal(size=(b, t, h, d)), jnp.float32)
        for _ in range(3)
    )

    def dense_ref(q, k, v):
        s = jnp.einsum("bqhd,bkhd->bhqk", q, k) / jnp.sqrt(
            jnp.asarray(d, jnp.float32)
        )
        s = jnp.where(jnp.tril(jnp.ones((t, t), bool))[None, None], s, -1e30)
        lse = jax.scipy.special.logsumexp(s, axis=-1)  # (B, H, T)
        out = jnp.einsum(
            "bhqk,bkhd->bqhd", jax.nn.softmax(s, axis=-1), v
        )
        return out, lse

    out_f, lse_f = flash_attention_with_lse(
        q, k, v, causal=True, block_q=16, block_k=16
    )
    out_d, lse_d = dense_ref(q, k, v)
    np.testing.assert_allclose(np.asarray(out_f), np.asarray(out_d), atol=1e-5)
    np.testing.assert_allclose(np.asarray(lse_f), np.asarray(lse_d), atol=1e-5)

    # gradient parity with BOTH cotangents live (out and lse)
    co = jnp.asarray(rng.normal(size=out_d.shape), jnp.float32)
    cl = jnp.asarray(rng.normal(size=lse_d.shape), jnp.float32)

    def loss_flash(q, k, v):
        o, l = flash_attention_with_lse(
            q, k, v, causal=True, block_q=16, block_k=16
        )
        return (o * co).sum() + (l * cl).sum()

    def loss_dense(q, k, v):
        o, l = dense_ref(q, k, v)
        return (o * co).sum() + (l * cl).sum()

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gd = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for a, b_ in zip(gf, gd):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_), atol=2e-4)


@pytest.mark.parametrize("causal", [True, False])
def test_ring_flash_matches_ring_dense(causal):
    """Flash-inside-ring == the dense-block ring over a 4-device seq mesh,
    forward and gradients."""
    from jax.sharding import Mesh

    from ddl_tpu.parallel.ring_attention import make_ring_self_attention

    mesh = Mesh(np.array(jax.devices()[:4]), ("seq",))
    rng = np.random.default_rng(7)
    b, t, h, d = 2, 64, 2, 8  # T_local = 16
    q, k, v = (
        jnp.asarray(rng.normal(size=(b, t, h, d)), jnp.float32)
        for _ in range(3)
    )
    dense_ring = make_ring_self_attention(mesh, causal=causal)
    flash_ring = make_ring_self_attention(
        mesh, causal=causal, use_flash=True, flash_block=16
    )
    np.testing.assert_allclose(
        np.asarray(flash_ring(q, k, v)), np.asarray(dense_ring(q, k, v)),
        atol=1e-5,
    )
    co = jnp.asarray(rng.normal(size=q.shape), jnp.float32)
    gd = jax.grad(lambda *a: (dense_ring(*a) * co).sum(), (0, 1, 2))(q, k, v)
    gf = jax.grad(lambda *a: (flash_ring(*a) * co).sum(), (0, 1, 2))(q, k, v)
    for a, b_ in zip(gf, gd):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_), atol=2e-4)


def test_lm_ring_flash_matches_dense():
    """Full LM train step: attn_impl='ring' + flash=True == flash=False
    (same gradients) on a (data=2, seq=2) mesh."""
    import optax

    from ddl_tpu.models.transformer import LMConfig
    from ddl_tpu.parallel.sharding import LMMeshSpec
    from ddl_tpu.train.lm_steps import make_lm_step_fns

    states = {}
    for flash in (False, True):
        cfg = LMConfig(
            vocab_size=32, d_model=32, n_layers=2, n_heads=4, head_dim=8,
            d_ff=64, compute_dtype="float32", remat=False,
            attn_impl="ring", flash=flash,
        )
        fns = make_lm_step_fns(
            cfg, LMMeshSpec(data=2, seq=2), optax.adam(1e-3),
            jax.random.key(0), 4, 32, devices=jax.devices()[:4],
        )
        toks = jnp.asarray(
            np.random.default_rng(0).integers(0, 32, (4, 33))
        )
        s1, m = fns.train(fns.init_state(), toks[:, :-1], toks[:, 1:])
        states[flash] = (float(m["loss"]), jax.device_get(s1.params))
    assert abs(states[False][0] - states[True][0]) < 1e-5
    err = jax.tree.reduce(max, jax.tree.map(
        lambda a, b: float(np.max(np.abs(a - b))),
        states[False][1], states[True][1]))
    assert err < 1e-4


def test_flash_gqa_matches_dense_and_repeated():
    """Grouped K/V through the Pallas kernel: forward equals the grouped
    dense core; gradients equal the repeat-then-attend formulation with
    dK/dV accumulated over the query-head group at Hkv granularity."""
    from ddl_tpu.ops.attention import dense_attention

    rng = np.random.default_rng(12)
    b, t, hq, hkv, d = 2, 128, 8, 2, 16
    g = hq // hkv
    q = jnp.asarray(rng.normal(size=(b, t, hq, d)), jnp.float32)
    k = jnp.asarray(rng.normal(size=(b, t, hkv, d)), jnp.float32)
    v = jnp.asarray(rng.normal(size=(b, t, hkv, d)), jnp.float32)
    for window in (0, 32):
        out = flash_attention(
            q, k, v, causal=True, window=window, block_q=32, block_k=32
        )
        ref = dense_attention(q, k, v, causal=True, window=window)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), atol=2e-5, rtol=1e-4
        )

        def loss(a, bb, c):
            return flash_attention(
                a, bb, c, causal=True, window=window, block_q=32, block_k=32
            ).astype(jnp.float32).sum()

        gq, gk, gv = jax.grad(loss, (0, 1, 2))(q, k, v)
        assert gk.shape == k.shape  # gradients stay at Hkv heads
        rq, rk_rep, rv_rep = jax.grad(
            lambda a, bb, c: loss(
                a, jnp.repeat(bb, g, 2), jnp.repeat(c, g, 2)
            ),
            (0, 1, 2),
        )(q, k, v)
        np.testing.assert_allclose(np.asarray(gq), np.asarray(rq), atol=2e-5)
        np.testing.assert_allclose(np.asarray(gk), np.asarray(rk_rep), atol=2e-5)
        np.testing.assert_allclose(np.asarray(gv), np.asarray(rv_rep), atol=2e-5)


def test_flash_gqa_lse_matches_repeated():
    from ddl_tpu.ops.flash_attention import flash_attention_with_lse

    rng = np.random.default_rng(13)
    q = jnp.asarray(rng.normal(size=(1, 64, 4, 8)), jnp.float32)
    k = jnp.asarray(rng.normal(size=(1, 64, 2, 8)), jnp.float32)
    v = jnp.asarray(rng.normal(size=(1, 64, 2, 8)), jnp.float32)
    o1, l1 = flash_attention_with_lse(q, k, v, causal=True, block_q=32, block_k=32)
    o2, l2 = flash_attention_with_lse(
        q, jnp.repeat(k, 2, 2), jnp.repeat(v, 2, 2), causal=True,
        block_q=32, block_k=32,
    )
    np.testing.assert_allclose(np.asarray(o1), np.asarray(o2), atol=1e-6)
    np.testing.assert_allclose(np.asarray(l1), np.asarray(l2), atol=1e-6)


def test_flash_rejects_bad_kv_heads():
    q = jnp.zeros((1, 32, 6, 8), jnp.float32)
    k = jnp.zeros((1, 32, 4, 8), jnp.float32)
    with pytest.raises(ValueError, match="divide"):
        flash_attention(q, k, k, causal=True)


def test_flash_kv_offset_empty_band_rows_are_zero():
    """With kv_offset a live tile can hold rows whose whole band is masked;
    those rows must output exactly zero (and a floor lse), not mean-of-V
    garbage (round-3 review finding)."""
    from ddl_tpu.ops.flash_attention import flash_attention_with_lse

    rng = np.random.default_rng(3)
    t = 32
    q, k, v = (
        jnp.asarray(rng.normal(size=(1, t, 2, 8)), jnp.float32)
        for _ in range(3)
    )
    # offset t, window 8: row q sees k_loc > q + t - 8, so rows >= 7
    # see nothing in this block (empty band inside a live tile)
    out, lse = flash_attention_with_lse(
        q, k, v, causal=True, window=8, kv_offset=t, block_q=8, block_k=8
    )
    np.testing.assert_array_equal(np.asarray(out[:, 7:]), 0.0)
    assert np.all(np.asarray(lse[:, :, 7:]) < -1e29)
    # visible rows equal the dense cross-block band (the dense core's
    # fully-masked rows produce uniform-softmax output, so compare only
    # the rows with a non-empty band)
    pos_q = np.arange(t)[:, None]
    pos_k = np.arange(t)[None, :] - t
    mask = (pos_k <= pos_q) & (pos_k > pos_q - 8)
    want = dense_attention(q, k, v, mask=jnp.asarray(mask))
    got = np.asarray(out[:, :7])
    np.testing.assert_allclose(got, np.asarray(want)[:, :7], atol=2e-5)
    # backward stays finite and zero for the empty rows
    g = jax.grad(
        lambda x: flash_attention_with_lse(
            x, k, v, causal=True, window=8, kv_offset=t,
            block_q=8, block_k=8,
        )[0].sum()
    )(q)
    assert bool(jnp.isfinite(g).all())
    np.testing.assert_array_equal(np.asarray(g[:, 7:]), 0.0)
