"""Residual dropout (LMConfig.dropout_rate / ViTConfig.dropout_rate).

Train steps derive a fresh dropout rng from the step counter; eval and
decode stay deterministic.  The pipeline paths fold (microbatch, stage,
layer) into the per-step key so GPipe's autodiff replay and 1F1B's
backward-tick recompute reproduce identical masks — the two schedules
stay gradient-equivalent even with dropout on.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax

from ddl_tpu.models.transformer import LMConfig
from ddl_tpu.models.vit import ViTConfig
from ddl_tpu.parallel.sharding import LMMeshSpec
from ddl_tpu.train.lm_steps import make_lm_step_fns
from ddl_tpu.train.vit_steps import make_vit_step_fns

B, T = 8, 8


def _lm_cfg(**kw):
    base = dict(vocab_size=32, d_model=32, n_layers=2, n_heads=2, head_dim=16,
                d_ff=64, compute_dtype="float32", remat=False)
    base.update(kw)
    return LMConfig(**base)


def _toks(seed=0):
    t = np.random.default_rng(seed).integers(0, 32, (B, T + 1))
    return jnp.asarray(t[:, :-1]), jnp.asarray(t[:, 1:])


def test_lm_dropout_trains_stochastically_evals_deterministically():
    cfg = _lm_cfg(dropout_rate=0.5)
    fns = make_lm_step_fns(cfg, LMMeshSpec(data=2), optax.sgd(0.0),
                           jax.random.key(0), B, T,
                           devices=jax.devices()[:2])
    inp, tgt = _toks()
    state = fns.init_state()
    # lr=0 keeps params fixed; differing losses across steps can only come
    # from the per-step dropout rng
    state, m1 = fns.train(state, inp, tgt)
    state, m2 = fns.train(state, inp, tgt)
    assert float(m1["loss"]) != float(m2["loss"])
    # eval is deterministic and dropout-free
    e1 = fns.evaluate(state, inp, tgt)
    e2 = fns.evaluate(state, inp, tgt)
    assert float(e1["loss"]) == float(e2["loss"])
    assert float(e1["loss"]) != float(m1["loss"])


def test_lm_dropout_with_remat_and_accum():
    cfg = _lm_cfg(dropout_rate=0.3, remat=True)
    fns = make_lm_step_fns(cfg, LMMeshSpec(data=2), optax.adam(1e-2),
                           jax.random.key(0), B, T, accum_steps=2,
                           devices=jax.devices()[:2])
    inp, tgt = _toks(1)
    state, m = fns.train(fns.init_state(), inp, tgt)
    assert np.isfinite(float(m["loss"]))
    assert int(jax.device_get(state.step)) == 1


def test_lm_pipeline_dropout_deterministic_and_schedule_equivalent():
    """Pipelined dropout: same seed/schedule -> identical run; dropout
    actually changes the loss; gpipe and 1f1b draw identical
    (microbatch, stage, layer) masks so their updates still agree."""
    tx = optax.adam(1e-2)
    inp, tgt = _toks()

    def run(sched, rate):
        cfg = _lm_cfg(dropout_rate=rate, n_layers=4, remat=True)
        fns = make_lm_step_fns(cfg, LMMeshSpec(data=2, pipe=2), tx,
                               jax.random.key(0), B, T, num_microbatches=4,
                               pipeline_schedule=sched,
                               devices=jax.devices()[:4])
        state, m = fns.train(fns.init_state(), inp, tgt)
        ev = fns.evaluate(state, inp, tgt)
        return float(m["loss"]), jax.device_get(state.params), float(ev["loss"])

    l_a, p_a, e_a = run("gpipe", 0.3)
    l_b, p_b, e_b = run("gpipe", 0.3)
    assert l_a == l_b and e_a == e_b  # deterministic per (seed, step)
    l_0, _, _ = run("gpipe", 0.0)
    assert l_a != l_0  # dropout is live inside the manual region
    l_f, p_f, _ = run("1f1b", 0.3)
    assert abs(l_a - l_f) < 1e-5
    err = jax.tree.reduce(max, jax.tree.map(
        lambda a, b: float(np.max(np.abs(np.asarray(a) - np.asarray(b)))),
        p_a, p_f))
    assert err < 1e-5, err


def test_lm_interleaved_dropout_deterministic():
    """Dropout under the interleaved schedule: masks key on the GLOBAL
    stage (c*P+s), so the run is deterministic per (seed, step) and
    dropout is live (masks differ from the V=1 schedule by construction —
    different stage decomposition — so no cross-V parity is claimed)."""
    tx = optax.adam(1e-2)
    inp, tgt = _toks()

    def run(rate):
        cfg = _lm_cfg(dropout_rate=rate, n_layers=4, remat=True)
        fns = make_lm_step_fns(cfg, LMMeshSpec(data=2, pipe=2), tx,
                               jax.random.key(0), B, T, num_microbatches=4,
                               virtual_stages=2, devices=jax.devices()[:4])
        state, m = fns.train(fns.init_state(), inp, tgt)
        return float(m["loss"])

    l_a, l_b, l_0 = run(0.3), run(0.3), run(0.0)
    assert l_a == l_b  # deterministic per (seed, step)
    assert l_a != l_0  # dropout is live inside the interleaved loop
    assert np.isfinite(l_a)


def test_vit_pipeline_dropout_runs():
    vcfg = ViTConfig(image_size=16, patch_size=4, d_model=32, n_layers=2,
                     n_heads=4, head_dim=8, d_ff=64, compute_dtype="float32",
                     dropout_rate=0.3)
    rng = np.random.default_rng(2)
    imgs = jnp.asarray(rng.integers(0, 255, (B, 16, 16, 3)).astype(np.uint8))
    labels = jnp.asarray(rng.integers(0, 5, (B,)).astype(np.int32))
    out = {}
    for sched in ("gpipe", "1f1b", "zb"):
        fns = make_vit_step_fns(vcfg, LMMeshSpec(pipe=2), optax.adam(1e-3),
                                jax.random.key(0), B, num_microbatches=2,
                                pipeline_schedule=sched,
                                devices=jax.devices()[:2])
        state, m = fns.train(fns.init_state(), imgs, labels)
        assert np.isfinite(float(m["loss"]))
        out[sched] = (float(m["loss"]), jax.device_get(state.params))
    assert abs(out["gpipe"][0] - out["1f1b"][0]) < 1e-5
    err = jax.tree.reduce(max, jax.tree.map(
        lambda a, b: float(np.max(np.abs(np.asarray(a) - np.asarray(b)))),
        out["gpipe"][1], out["1f1b"][1]))
    assert err < 1e-5, err
    # the zb W pass refolds the mask key from the queued microbatch
    # index — identical masks, so zb matches 1f1b exactly
    err_zb = jax.tree.reduce(max, jax.tree.map(
        lambda a, b: float(np.max(np.abs(np.asarray(a) - np.asarray(b)))),
        out["zb"][1], out["1f1b"][1]))
    assert err_zb <= 1e-6, err_zb


def test_vit_dropout():
    cfg = ViTConfig(image_size=16, patch_size=4, d_model=32, n_layers=2,
                    n_heads=4, head_dim=8, d_ff=64, compute_dtype="float32",
                    remat=False, dropout_rate=0.5)
    fns = make_vit_step_fns(cfg, LMMeshSpec(data=2), optax.sgd(0.0),
                            jax.random.key(0), B, devices=jax.devices()[:2])
    rng = np.random.default_rng(2)
    imgs = jnp.asarray(rng.integers(0, 255, (B, 16, 16, 3)).astype(np.uint8))
    labels = jnp.asarray(rng.integers(0, 5, (B,)).astype(np.int32))
    state = fns.init_state()
    state, m1 = fns.train(state, imgs, labels)
    state, m2 = fns.train(state, imgs, labels)
    assert float(m1["loss"]) != float(m2["loss"])
    l1 = np.asarray(fns.evaluate(state, imgs))
    l2 = np.asarray(fns.evaluate(state, imgs))
    np.testing.assert_array_equal(l1, l2)


def test_decode_unaffected_by_dropout_config():
    from ddl_tpu.infer import make_lm_generator
    from ddl_tpu.models.transformer import TransformerLM
    import flax.linen as nn

    cfg = _lm_cfg(dropout_rate=0.5)
    model = TransformerLM(cfg, None)
    params = nn.meta.unbox(
        model.init(jax.random.key(0), jnp.zeros((2, 4), jnp.int32))["params"]
    )
    gen = make_lm_generator(cfg, prompt_len=4, max_new=3, batch=2,
                            devices=jax.devices()[:1])
    prompt = jnp.asarray(np.random.default_rng(3).integers(0, 32, (2, 4)))
    a = np.asarray(gen(params, prompt))
    b = np.asarray(gen(params, prompt))
    np.testing.assert_array_equal(a, b)  # decode is deterministic
