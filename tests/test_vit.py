"""ViT family (models/vit.py + train/vit_steps.py).

Parity discipline matches the other families: sharded configurations must
reproduce the single-device run numerically, and the model must actually
learn (overfit a tiny batch).
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from ddl_tpu.models.vit import ViT, ViTConfig
from ddl_tpu.parallel.sharding import LMMeshSpec
from ddl_tpu.train.vit_steps import make_vit_step_fns


def _cfg(**kw):
    base = dict(
        image_size=16,
        patch_size=4,
        num_classes=5,
        d_model=32,
        n_layers=2,
        n_heads=4,
        head_dim=8,
        d_ff=64,
        compute_dtype="float32",
        remat=False,
    )
    base.update(kw)
    return ViTConfig(**base)


def _batch(b=8, size=16, seed=0):
    rng = np.random.default_rng(seed)
    imgs = rng.integers(0, 255, (b, size, size, 3)).astype(np.uint8)
    labels = rng.integers(0, 5, (b,)).astype(np.int32)
    return jnp.asarray(imgs), jnp.asarray(labels)


def test_forward_shape_and_bidirectional():
    """Logits shape; and a behavioral causality check: with causal=False,
    position 0's representation must depend on later positions (with
    causal=True it cannot)."""
    cfg = _cfg()
    imgs, _ = _batch()
    model = ViT(cfg)
    params = model.init(jax.random.key(0), jnp.zeros((8, 16, 16, 3)))["params"]
    logits = model.apply({"params": params}, imgs.astype(jnp.float32))
    assert logits.shape == (8, 5)
    assert bool(jnp.isfinite(logits).all())
    assert cfg.block_config().causal is False

    # behavioral: run the shared transformer LM with both causal settings —
    # changing the LAST token must move position-0 logits iff bidirectional
    from ddl_tpu.models.transformer import LMConfig, TransformerLM

    base = dict(vocab_size=16, d_model=16, n_layers=1, n_heads=2, head_dim=8,
                d_ff=32, compute_dtype="float32", remat=False)
    toks = jnp.asarray(np.arange(6)[None, :] % 16)
    toks2 = toks.at[0, -1].set(9)
    for causal in (True, False):
        m = TransformerLM(LMConfig(causal=causal, **base), None)
        p = m.init(jax.random.key(0), toks)["params"]
        a, _ = m.apply({"params": p}, toks)
        b, _ = m.apply({"params": p}, toks2)
        moved = float(jnp.max(jnp.abs(a[0, 0] - b[0, 0])))
        if causal:
            assert moved == 0.0
        else:
            assert moved > 1e-6


def test_non_dense_impls_reject_bidirectional():
    from ddl_tpu.models.transformer import LMConfig
    from ddl_tpu.train.lm_steps import make_lm_step_fns

    cfg = LMConfig(vocab_size=16, d_model=16, n_layers=2, n_heads=2,
                   head_dim=8, d_ff=32, compute_dtype="float32",
                   causal=False, attn_impl="ulysses")
    with pytest.raises(ValueError, match="causal=False"):
        make_lm_step_fns(cfg, LMMeshSpec(), optax.adam(1e-3),
                         jax.random.key(0), 4, 8, devices=jax.devices()[:1])


def test_dp_tp_matches_single():
    cfg = _cfg()
    tx = optax.adam(1e-3)
    imgs, labels = _batch()

    single = make_vit_step_fns(cfg, LMMeshSpec(), tx, jax.random.key(0), 8,
                               devices=jax.devices()[:1])
    s0 = single.init_state()
    p_ref = jax.device_get(s0.params)
    s1, m_ref = single.train(s0, imgs, labels)

    sharded = make_vit_step_fns(cfg, LMMeshSpec(data=2, model=2), tx,
                                jax.random.key(0), 8,
                                devices=jax.devices()[:4])
    t0 = sharded.init_state()
    # same rng -> same init
    err0 = jax.tree.reduce(max, jax.tree.map(
        lambda a, b: float(np.max(np.abs(a - b))),
        p_ref, jax.device_get(t0.params)))
    assert err0 < 1e-6
    t1, m = sharded.train(t0, imgs, labels)
    assert abs(float(m["loss"]) - float(m_ref["loss"])) < 1e-5
    err = jax.tree.reduce(max, jax.tree.map(
        lambda a, b: float(np.max(np.abs(a - b))),
        jax.device_get(s1.params), jax.device_get(t1.params)))
    assert err < 1e-4


def test_fsdp_runs():
    cfg = _cfg(fsdp=True)
    fns = make_vit_step_fns(cfg, LMMeshSpec(data=4), optax.adam(1e-3),
                            jax.random.key(0), 8, devices=jax.devices()[:4])
    state = fns.init_state()
    imgs, labels = _batch()
    state, m = fns.train(state, imgs, labels)
    assert np.isfinite(float(m["loss"]))
    assert int(jax.device_get(state.step)) == 1


def test_overfits_tiny_batch():
    """The model must drive loss down hard on a fixed tiny batch."""
    cfg = _cfg()
    fns = make_vit_step_fns(cfg, LMMeshSpec(data=2), optax.adam(3e-3),
                            jax.random.key(1), 8, devices=jax.devices()[:2])
    state = fns.init_state()
    imgs, labels = _batch(seed=3)
    first = None
    for _ in range(60):
        state, m = fns.train(state, imgs, labels)
        if first is None:
            first = float(m["loss"])
    last = float(m["loss"])
    assert last < 0.1 * first, (first, last)
    assert float(m["accuracy"]) == 1.0


def test_pipeline_matches_single():
    """DP x PP ViT (data=2, pipe=2, 2 microbatches) must reproduce the
    single-device run: same loss, same post-Adam parameters."""
    cfg = _cfg()
    tx = optax.adam(1e-3)
    imgs, labels = _batch()

    single = make_vit_step_fns(cfg, LMMeshSpec(), tx, jax.random.key(0), 8,
                               devices=jax.devices()[:1])
    s1, m_ref = single.train(single.init_state(), imgs, labels)
    p_ref = jax.device_get(s1.params)

    pp = make_vit_step_fns(cfg, LMMeshSpec(data=2, pipe=2), tx,
                           jax.random.key(0), 8, devices=jax.devices()[:4],
                           num_microbatches=2)
    t1, m = pp.train(pp.init_state(), imgs, labels)
    assert abs(float(m["loss"]) - float(m_ref["loss"])) < 1e-5
    # compare per-layer: stage-major stacked blocks vs flat block{i}
    pp_params = jax.device_get(t1.params)
    for p in range(2):
        for j in range(1):  # 2 layers / 2 stages
            flat = p_ref[f"block{p * 1 + j}"]
            stacked = jax.tree.map(lambda x: x[p, j], pp_params["blocks"])
            err = jax.tree.reduce(max, jax.tree.map(
                lambda a, b: float(np.max(np.abs(a - b))), flat, stacked))
            assert err < 1e-4, (p, j, err)
    for src, dst in ((p_ref["patch_embed"], pp_params["embed"]["patch_embed"]),
                     (p_ref["pos_embed"], pp_params["embed"]["pos_embed"]),
                     (p_ref["norm_f"], pp_params["head"]["norm_f"]),
                     (p_ref["head"], pp_params["head"]["head"])):
        err = jax.tree.reduce(max, jax.tree.map(
            lambda a, b: float(np.max(np.abs(a - b))), src, dst))
        assert err < 1e-4


def test_pipeline_1f1b_matches_gpipe():
    """The shared 1F1B clock loop drives the ViT pipeline too: same
    gradients as the GPipe schedule on a DP x TP x PP mesh."""
    cfg = _cfg()
    tx = optax.adam(1e-3)
    imgs, labels = _batch()
    out = {}
    for sched in ("gpipe", "1f1b"):
        fns = make_vit_step_fns(
            cfg, LMMeshSpec(data=2, model=2, pipe=2), tx, jax.random.key(0),
            8, devices=jax.devices()[:8], num_microbatches=2,
            pipeline_schedule=sched,
        )
        s1, m = fns.train(fns.init_state(), imgs, labels)
        out[sched] = (
            float(m["loss"]), float(m["accuracy"]), jax.device_get(s1.params)
        )
    assert abs(out["gpipe"][0] - out["1f1b"][0]) < 1e-5
    assert abs(out["gpipe"][1] - out["1f1b"][1]) < 1e-6
    err = jax.tree.reduce(max, jax.tree.map(
        lambda a, b: float(np.max(np.abs(a - b))),
        out["gpipe"][2], out["1f1b"][2]))
    assert err < 1e-5, err


@pytest.mark.parametrize("dropout", [0.0, 0.1], ids=["nodrop", "dropout"])
def test_pipeline_zb_matches_gpipe_and_1f1b(dropout):
    """The zero-bubble (B/W-split) schedule on the ViT pipeline: one
    step matches BOTH reference schedules to 1e-6 (the acceptance
    bound), and a 3-step Adam trajectory stays within 1e-6 of 1F1B —
    the zb backward is the same arithmetic as 1F1B's joint vjp, split
    in two, so it adds nothing to the known 1F1B-vs-GPipe head
    formulation drift."""
    cfg = _cfg(n_layers=4, dropout_rate=dropout)
    tx = optax.adam(1e-2)
    imgs, labels = _batch()
    out = {}
    for sched in ("gpipe", "1f1b", "zb"):
        fns = make_vit_step_fns(
            cfg, LMMeshSpec(pipe=2), tx, jax.random.key(0),
            8, devices=jax.devices()[:2], num_microbatches=4,
            pipeline_schedule=sched,
        )
        st = fns.init_state()
        st, m = fns.train(st, imgs, labels)
        step1 = jax.device_get(st.params)
        for _ in range(2):
            st, m = fns.train(st, imgs, labels)
        out[sched] = (step1, float(m["loss"]), jax.device_get(st.params))

    def err(a, b):
        return jax.tree.reduce(max, jax.tree.map(
            lambda x, y: float(np.max(np.abs(x - y))), a, b))

    assert err(out["zb"][0], out["gpipe"][0]) <= 1e-6
    assert err(out["zb"][0], out["1f1b"][0]) <= 1e-6
    assert abs(out["zb"][1] - out["1f1b"][1]) <= 1e-6
    assert err(out["zb"][2], out["1f1b"][2]) <= 1e-6


def test_eval_matches_train_logits():
    cfg = _cfg()
    fns = make_vit_step_fns(cfg, LMMeshSpec(data=2), optax.adam(1e-3),
                            jax.random.key(0), 8, devices=jax.devices()[:2])
    state = fns.init_state()
    imgs, labels = _batch()
    logits = fns.evaluate(state, imgs)
    assert logits.shape == (8, 5)
    assert bool(jnp.isfinite(jnp.asarray(logits)).all())


def test_pipeline_interleaved_matches_single():
    """Interleaved virtual stages for the ViT pipeline (shared clock loop,
    self-describing blocks['interleaved'] layout): DP x PP, V=2 over 4
    encoder layers, exact single-device parity."""
    cfg = _cfg(n_layers=4)
    tx = optax.adam(1e-3)
    imgs, labels = _batch()
    single = make_vit_step_fns(cfg, LMMeshSpec(), tx, jax.random.key(0), 8,
                               devices=jax.devices()[:1])
    s1, m_ref = single.train(single.init_state(), imgs, labels)

    pp = make_vit_step_fns(cfg, LMMeshSpec(data=2, pipe=2), tx,
                           jax.random.key(0), 8, devices=jax.devices()[:4],
                           num_microbatches=2, virtual_stages=2)
    t1, m = pp.train(pp.init_state(), imgs, labels)
    assert abs(float(m["loss"]) - float(m_ref["loss"])) < 1e-5
    pp_params = jax.device_get(t1.params)
    blocks = pp_params["blocks"]["interleaved"]
    ref = jax.device_get(s1.params)
    # layer ell = (c*2 + s)*1 + 0 lives at [s, c]
    for ell in range(4):
        s_, c_ = ell % 2, ell // 2
        stacked = jax.tree.map(lambda x: x[s_, c_, 0], blocks)
        err = jax.tree.reduce(max, jax.tree.map(
            lambda a, b: float(np.max(np.abs(a - b))),
            ref[f"block{ell}"], stacked))
        assert err < 1e-4, (ell, err)


def test_pipeline_interleaved_1f1b_matches_interleaved_gpipe():
    """The combined interleaved-1F1B schedule on the ViT pipeline (shared
    clock loop with the LM): same gradients as interleaved GPipe."""
    cfg = _cfg(n_layers=4, dropout_rate=0.1)
    tx = optax.adam(1e-3)
    imgs, labels = _batch()
    out = {}
    for sched in ("gpipe", "1f1b"):
        fns = make_vit_step_fns(cfg, LMMeshSpec(data=2, pipe=2), tx,
                                jax.random.key(0), 8,
                                devices=jax.devices()[:4],
                                num_microbatches=4, virtual_stages=2,
                                pipeline_schedule=sched)
        s1, m = fns.train(fns.init_state(), imgs, labels)
        out[sched] = (float(m["loss"]), jax.device_get(s1.params))
    assert abs(out["gpipe"][0] - out["1f1b"][0]) < 1e-5
    err = jax.tree.reduce(max, jax.tree.map(
        lambda a, b: float(np.max(np.abs(a - b))),
        out["gpipe"][1], out["1f1b"][1]))
    assert err < 5e-5


def test_vit_gqa_sharded_matches_single():
    """Grouped-query attention in the ViT encoder (bidirectional blocks,
    n_kv_heads pass-through via block_config): TP-sharded == single, loss
    AND post-Adam params (the reduced K/V kernels' gradients shard too)."""
    cfg = _cfg(n_kv_heads=2)
    tx = optax.adam(1e-3)
    imgs, labels = _batch()
    out = {}
    for name, spec in (("single", LMMeshSpec()), ("tp", LMMeshSpec(data=2, model=2))):
        fns = make_vit_step_fns(cfg, spec, tx, jax.random.key(0), 8,
                                devices=jax.devices()[: spec.num_devices])
        s1, m = fns.train(fns.init_state(), imgs, labels)
        out[name] = (float(m["loss"]), jax.device_get(s1.params))
    assert abs(out["single"][0] - out["tp"][0]) < 1e-4
    # reduced K/V projection really in the tree: (d_model, Hkv*Dh)
    assert out["single"][1]["block0"]["attn"]["k"]["kernel"].shape == (32, 16)
    err = jax.tree.reduce(max, jax.tree.map(
        lambda a, b: float(np.max(np.abs(a - b))),
        out["single"][1], out["tp"][1]))
    assert err < 1e-4

    with pytest.raises(ValueError, match="n_kv_heads"):
        make_vit_step_fns(_cfg(n_kv_heads=2), LMMeshSpec(model=4), tx,
                          jax.random.key(0), 8, devices=jax.devices()[:4])
