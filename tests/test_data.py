"""Data pipeline tests: sampler semantics vs torch.DistributedSampler, loaders."""

import numpy as np
import pytest

from ddl_tpu.data import (
    AptosImageDataset,
    DataLoader,
    ShardedEpochSampler,
    SyntheticAptosDataset,
)


class TestShardedEpochSampler:
    def test_partition_complete_and_disjoint(self):
        n, shards = 103, 4
        all_idx = []
        for r in range(shards):
            s = ShardedEpochSampler(n, shards, r, shuffle=True, drop_last=True, seed=7)
            s.set_epoch(3)
            idx = s.indices()
            assert len(idx) == n // shards
            all_idx.append(idx)
        flat = np.concatenate(all_idx)
        assert len(np.unique(flat)) == len(flat)  # disjoint

    def test_no_drop_last_pads_by_wraparound(self):
        n, shards = 10, 4
        lengths = set()
        flat = []
        for r in range(shards):
            s = ShardedEpochSampler(n, shards, r, shuffle=False, drop_last=False)
            idx = s.indices()
            lengths.add(len(idx))
            flat.extend(idx)
        assert lengths == {3}  # ceil(10/4), equal on every shard
        assert set(flat) == set(range(n))  # every example appears

    def test_sentinel_padding_covers_each_example_exactly_once(self):
        n, shards = 10, 4
        flat = []
        lengths = set()
        for r in range(shards):
            s = ShardedEpochSampler(
                n, shards, r, shuffle=False, drop_last=False, pad_mode="sentinel"
            )
            idx = s.indices()
            lengths.add(len(idx))
            flat.extend(idx)
        assert lengths == {3}  # equal shards (lock-step)
        flat = np.asarray(flat)
        real = flat[flat >= 0]
        assert sorted(real) == list(range(n))  # exactly once, no wrap dupes
        assert (flat < 0).sum() == 3 * shards - n

    def test_bad_pad_mode_raises(self):
        with pytest.raises(ValueError):
            ShardedEpochSampler(10, pad_mode="nope")

    def test_epoch_reshuffles(self):
        s = ShardedEpochSampler(100, 2, 0, shuffle=True, seed=1)
        s.set_epoch(0)
        a = s.indices().copy()
        s.set_epoch(1)
        b = s.indices()
        assert not np.array_equal(a, b)
        s.set_epoch(0)
        np.testing.assert_array_equal(a, s.indices())  # deterministic per epoch

    def test_matches_torch_distributed_sampler_invariants(self):
        """Same shard sizes and coverage as torch's DistributedSampler."""
        torch = pytest.importorskip("torch")
        from torch.utils.data import DistributedSampler

        class _DS(torch.utils.data.Dataset):
            def __len__(self):
                return 101

            def __getitem__(self, i):
                return i

        for drop_last in (True, False):
            torch_lens, ours_lens = [], []
            for r in range(3):
                ts = DistributedSampler(
                    _DS(), num_replicas=3, rank=r, shuffle=True, drop_last=drop_last
                )
                ts.set_epoch(5)
                torch_lens.append(len(list(ts)))
                s = ShardedEpochSampler(101, 3, r, shuffle=True, drop_last=drop_last)
                s.set_epoch(5)
                ours_lens.append(len(s.indices()))
            assert torch_lens == ours_lens


class TestSynthetic:
    def test_deterministic(self):
        d = SyntheticAptosDataset(16, image_size=32, seed=3)
        img1, lab1 = d[5]
        img2, lab2 = d[5]
        np.testing.assert_array_equal(img1, img2)
        assert lab1 == lab2
        assert img1.dtype == np.uint8 and img1.shape == (32, 32, 3)

    def test_classes_are_separable(self):
        """Blob positions must differ by class (the learnability signal)."""
        d = SyntheticAptosDataset(200, image_size=32, seed=0)
        means = {}
        for c in range(5):
            idxs = [i for i in range(200) if d.labels[i] == c][:10]
            imgs = np.stack([d[i][0] for i in idxs]).astype(np.float32)
            # centroid of brightness
            m = imgs.mean(axis=(0, 3))
            yy, xx = np.mgrid[0:32, 0:32]
            w = m - m.min()
            means[c] = (float((w * yy).sum() / w.sum()), float((w * xx).sum() / w.sum()))
        centers = np.array(list(means.values()))
        dists = np.linalg.norm(centers[:, None] - centers[None, :], axis=-1)
        assert (dists + np.eye(5) * 99).min() > 1.5


class TestAptosImageDataset:
    def test_reads_csv_and_pngs(self, tmp_path):
        from PIL import Image

        (tmp_path / "imgs").mkdir()
        with open(tmp_path / "meta.csv", "w") as f:
            f.write("new_id_code,diagnosis\nabc,2\nxyz,4\n")
        for name, shade in (("abc", 10), ("xyz", 200)):
            Image.fromarray(np.full((8, 8, 3), shade, np.uint8)).save(
                tmp_path / "imgs" / f"{name}.png"
            )
        ds = AptosImageDataset(tmp_path / "meta.csv", tmp_path / "imgs", "new_id_code")
        assert len(ds) == 2
        img, label = ds[1]
        assert label == 4
        assert img.shape == (8, 8, 3) and img[0, 0, 0] == 200

    def test_missing_column_raises(self, tmp_path):
        with open(tmp_path / "meta.csv", "w") as f:
            f.write("id,diagnosis\n1,0\n")
        with pytest.raises(ValueError):
            AptosImageDataset(tmp_path / "meta.csv", tmp_path, "new_id_code")


class TestDataLoader:
    def test_shapes_and_coverage(self):
        d = SyntheticAptosDataset(50, image_size=16, seed=0)
        dl = DataLoader(d, batch_size=8, shuffle=True, drop_last=True, num_workers=2)
        batches = list(dl)
        assert len(batches) == len(dl) == 6
        for imgs, labs in batches:
            assert imgs.shape == (8, 16, 16, 3) and imgs.dtype == np.uint8
            assert labs.shape == (8,) and labs.dtype == np.int32

    def test_epoch_changes_order(self):
        d = SyntheticAptosDataset(24, image_size=8, seed=0)
        dl = DataLoader(d, batch_size=8, num_workers=0)
        dl.set_epoch(0)
        a = np.concatenate([l for _, l in dl])
        dl.set_epoch(1)
        b = np.concatenate([l for _, l in dl])
        assert not np.array_equal(a, b)

    def test_pad_last_batch_static_shapes_full_coverage(self):
        """Eval-mode loading: every batch has the static batch_size shape,
        padded rows carry label -1 + zero image, and every real sample
        appears exactly once."""
        d = SyntheticAptosDataset(13, image_size=8, seed=0)
        dl = DataLoader(
            d,
            batch_size=5,
            sampler=ShardedEpochSampler(
                13, shuffle=False, drop_last=False, pad_mode="sentinel"
            ),
            num_workers=0,
            drop_last=False,
            pad_last_batch=True,
        )
        batches = list(dl)
        assert len(batches) == 3
        labels = np.concatenate([l for _, l in batches])
        images = np.concatenate([i for i, _ in batches])
        assert all(i.shape == (5, 8, 8, 3) for i, _ in batches)  # static
        assert (labels >= 0).sum() == 13 and (labels == -1).sum() == 2
        assert (images[labels == -1] == 0).all()
        # real rows are the dataset in order, exactly once
        real = images[labels >= 0]
        expect = np.stack([d[i][0] for i in range(13)])
        np.testing.assert_array_equal(real, expect)
