"""Config system, CSV logger, launcher, smoke test, comm bench, analysis."""

import json

import numpy as np
import pytest

from ddl_tpu.config import Config, MeshConfig, apply_overrides, preset, to_dict


class TestConfig:
    def test_presets_match_reference_batching(self):
        # single.py:286 bs=30; ddp.py:335 bs=15/rank; pp.py:365 bs=30;
        # ddp_n_pp.py:371 bs=10/dp-row on a (3,2) mesh.
        assert preset("single").data.global_batch_size == 30
        assert preset("dp").data.global_batch_size == 15 * 2
        assert preset("pp").data.global_batch_size == 30
        dnp = preset("dp_pp")
        assert (dnp.mesh.data, dnp.mesh.pipe) == (3, 2)
        assert dnp.data.global_batch_size == 30
        assert dnp.train.num_microbatches == 5  # pp.py:378

    def test_overrides(self):
        cfg = preset("dp", **{"mesh.data": 4, "data.global_batch_size": 60})
        assert cfg.mesh.data == 4 and cfg.data.global_batch_size == 60
        cfg2 = preset("single", **{"train.max_epochs": "3"})
        assert cfg2.train.max_epochs == 3

    def test_unknown_override_rejected(self):
        with pytest.raises(KeyError):
            apply_overrides(Config(), {"train.nope": 1})

    def test_validation(self):
        bad = Config(strategy="pp", mesh=MeshConfig(2, 2))
        with pytest.raises(ValueError):
            bad.validate()
        bad2 = Config(strategy="pp", mesh=MeshConfig(1, 3))  # 3 != 2 stages
        with pytest.raises(ValueError):
            bad2.validate()

    def test_to_dict_json_serialisable(self):
        json.dumps(to_dict(preset("dp_pp")))


class TestCsvLogger:
    def test_row_schema(self, tmp_path):
        from ddl_tpu.utils import MetricLogger
        from ddl_tpu.utils.csv_logger import read_metric_csv

        lg = MetricLogger(tmp_path, "job-abc", global_rank=2, local_rank=0)
        lg.log("loss", 0.5, epoch=7)
        rows = read_metric_csv(tmp_path / "by_job_id" / "job-abc" / "loss.csv")
        (r,) = rows
        # reference row: [ts, job, grank, lrank, model_start_job, epoch, value]
        # (single.py:269)
        assert r["job_id"] == "job-abc"
        assert r["global_rank"] == 2
        assert r["model_start_job_id"] == "job-abc"
        assert r["epoch"] == 7 and r["value"] == 0.5

    def test_lineage_column_on_resume(self, tmp_path):
        from ddl_tpu.utils import MetricLogger
        from ddl_tpu.utils.csv_logger import read_metric_csv

        lg = MetricLogger(tmp_path, "job-new", model_start_job_id="job-old")
        lg.log("qwk", 0.9, epoch=0)
        (r,) = read_metric_csv(tmp_path / "by_job_id" / "job-new" / "qwk.csv")
        assert r["model_start_job_id"] == "job-old"

    def test_gradient_stats(self, tmp_path):
        from ddl_tpu.utils import MetricLogger

        lg = MetricLogger(tmp_path, "j")
        lg.log_gradient_stats({"w": np.array([1.0, -2.0]), "b": np.array([0.5])}, step=3)
        lines = (tmp_path / "gradient.csv").read_text().strip().splitlines()
        assert len(lines) == 2 and ",w," in lines[0]


class TestLauncher:
    def test_pod_commands(self):
        from ddl_tpu.launcher import JobSpec, pod_commands

        spec = JobSpec(preset="dp_pp", num_hosts=4, overrides=("mesh.data=8",))
        cmds = pod_commands(spec, coordinator_host="10.0.0.1")
        assert len(cmds) == 4
        assert "DDL_PROCESS_ID=3" in cmds[3]
        assert "DDL_NUM_PROCESSES=4" in cmds[0]
        assert "--preset dp_pp" in cmds[0] and "mesh.data=8" in cmds[0]
        # all hosts share one job id
        jid = [tok for tok in cmds[0].split() if tok.startswith("DDL_JOB_ID=")]
        assert all(jid[0] in c for c in cmds)

    def test_kubernetes_manifest(self):
        from ddl_tpu.launcher import JobSpec, kubernetes_manifest

        y = kubernetes_manifest(JobSpec(preset="dp", num_hosts=2))
        assert "parallelism: 2" in y and "google.com/tpu" in y


class TestSmoke:
    def test_mesh_collectives(self):
        from ddl_tpu.tools.smoke import run_smoke

        assert run_smoke(data=2, pipe=2)


class TestCommBench:
    def test_ping_pong(self):
        from ddl_tpu.bench.comm import ping_pong

        r = ping_pong(iterations=5, payload_elems=1024)
        assert r.times_ms.shape == (6,)
        assert np.isfinite(r.mean_ms) and r.mean_ms > 0
        assert r.one_way_gbps > 0

    @pytest.mark.parametrize(
        "op", ["psum", "all_gather", "reduce_scatter", "ppermute", "all_to_all"]
    )
    def test_collective_bandwidth(self, op):
        from ddl_tpu.bench.comm import collective_bandwidth

        r = collective_bandwidth(op, payload_elems=1024, iterations=3)
        assert np.isfinite(r["algbw_gbps"]) and r["algbw_gbps"] > 0

    def test_axis_sweep_covers_every_nontrivial_axis(self):
        """Per-axis attribution (the Ulysses all_to_all rides 'seq', DP
        grads ride 'data'): every axis with size > 1 gets every op; size-1
        axes are skipped."""
        import jax
        from jax.sharding import Mesh

        from ddl_tpu.bench.comm import COLLECTIVE_OPS, axis_bandwidth_sweep

        mesh = Mesh(
            np.array(jax.devices()[:8]).reshape(2, 1, 4),
            ("data", "pipe", "model"),
        )
        sweep = axis_bandwidth_sweep(mesh, payload_elems=512, iterations=2)
        assert set(sweep) == {"data", "model"}  # pipe=1 skipped
        for axis, per_op in sweep.items():
            assert set(per_op) == set(COLLECTIVE_OPS)
            for op, r in per_op.items():
                assert r["axis"] == axis
                assert np.isfinite(r["algbw_gbps"]) and r["algbw_gbps"] > 0, (
                    axis, op,
                )
        assert sweep["data"]["psum"]["devices"] == 2
        assert sweep["model"]["psum"]["devices"] == 4

    def test_run_comm_bench_writes_reference_csv(self, tmp_path):
        from ddl_tpu.bench.comm import run_comm_bench

        s = run_comm_bench(log_dir=tmp_path, job_id="commjob", iterations=3)
        lines = (tmp_path / "communication_time.csv").read_text().strip().splitlines()
        assert len(lines) == 4  # warmup + 3
        job, it, ms = lines[0].split(",")
        assert job == "commjob" and it == "0" and float(ms) > 0
        assert "psum_gbps" in s


class TestAnalysis:
    def test_aggregations(self, tmp_path):
        from ddl_tpu.bench.analysis import (
            comm_time_summary,
            epoch_time_per_job,
            final_epoch_quality,
        )
        from ddl_tpu.utils import MetricLogger

        for job, et in (("dp-aaa", 10.0), ("dp-bbb", 20.0), ("single-ccc", 30.0)):
            lg = MetricLogger(tmp_path, job)
            for epoch in range(2):
                lg.log("epoch_time", et + epoch, epoch)
                lg.log("qwk", 0.5 + epoch / 10, epoch)
                lg.log("loss", 1.0 - epoch / 10, epoch)
        per_job = epoch_time_per_job(tmp_path)
        assert per_job["dp-aaa"] == pytest.approx(10.5)
        quality = final_epoch_quality(tmp_path)
        assert quality["dp"]["qwk"] == pytest.approx(0.6)
        assert quality["single"]["loss"] == pytest.approx(0.9)
        with open(tmp_path / "communication_time.csv", "w") as f:
            f.write("j,0,100.0\nj,1,1.0\nj,2,3.0\n")
        s = comm_time_summary(tmp_path)
        assert s["j"]["mean_ms"] == pytest.approx(2.0)  # iteration 0 excluded
        assert s["j"]["init_ms"] == pytest.approx(100.0)


class TestGraftEntry:
    def test_dryrun_multichip(self):
        import __graft_entry__ as ge

        ge.dryrun_multichip(8)

    def test_entry_lowers(self):
        """The flagship forward must trace+lower under jit (full compile of
        densenet121 on CPU is exercised by the driver)."""
        import jax

        import __graft_entry__ as ge

        fn, args = ge.entry()
        jax.jit(fn).lower(*args)  # raises on any tracing/sharding error


class TestMfu:
    def test_compiled_step_flops_exact(self):
        import jax.numpy as jnp

        from ddl_tpu.bench.mfu import compiled_step_flops

        n = 64
        flops = compiled_step_flops(lambda x: x @ x, jnp.ones((n, n)))
        assert flops == 2 * n**3  # XLA counts 2mnk for a matmul

    def test_peak_lookup_prefix_precedence(self):
        from ddl_tpu.bench.mfu import PEAK_BF16_FLOPS, device_peak_flops

        class FakeDev:
            def __init__(self, kind):
                self.device_kind = kind

        assert device_peak_flops(FakeDev("TPU v5 lite")) == PEAK_BF16_FLOPS["TPU v5 lite"]
        assert device_peak_flops(FakeDev("TPU v5p")) == PEAK_BF16_FLOPS["TPU v5p"]
        assert device_peak_flops(FakeDev("TPU v4")) == PEAK_BF16_FLOPS["TPU v4"]
        assert device_peak_flops(FakeDev("cpu")) is None

    def test_mfu_on_cpu_is_none(self):
        from ddl_tpu.bench.mfu import mfu

        assert mfu(1e12, 0.01) is None  # CPU device: peak unknown

    def test_step_fns_expose_lower(self):
        """The set_mesh wrappers re-export jit's .lower so cost analysis
        can reach the compiled step (lm_steps/vit_steps _with_mesh)."""
        import jax
        import optax

        from ddl_tpu.models.transformer import LMConfig
        from ddl_tpu.parallel.sharding import LMMeshSpec
        from ddl_tpu.train.lm_steps import make_lm_step_fns

        cfg = LMConfig(
            vocab_size=32, d_model=32, n_layers=1, n_heads=2, head_dim=16,
            d_ff=64, compute_dtype="float32", remat=False,
        )
        fns = make_lm_step_fns(
            cfg, LMMeshSpec(), optax.adam(1e-3), jax.random.key(0), 2, 8
        )
        assert hasattr(fns.train, "lower")
        import jax.numpy as jnp

        from ddl_tpu.bench.mfu import compiled_step_flops

        state = fns.init_state()
        toks = jnp.zeros((2, 8), jnp.int32)
        flops = compiled_step_flops(fns.train, state, toks, toks)
        assert flops > 0


class TestMemoryStats:
    def test_graceful_none_without_stats(self):
        from ddl_tpu.utils.memory import hbm_stats

        class NoStats:
            def memory_stats(self):
                return None

        class Raises:
            def memory_stats(self):
                raise RuntimeError("unsupported")

        assert hbm_stats(NoStats()) is None
        assert hbm_stats(Raises()) is None
        # and whatever the ambient backend returns, it's a dict or None
        assert hbm_stats() is None or isinstance(hbm_stats(), dict)

    def test_shape_when_backend_reports(self):
        from ddl_tpu.utils.memory import hbm_stats

        class FakeDev:
            def memory_stats(self):
                return {"bytes_in_use": 10, "peak_bytes_in_use": 99,
                        "bytes_limit": 1000}

        out = hbm_stats(FakeDev())
        assert out == {"bytes_in_use": 10, "peak_bytes_in_use": 99,
                       "bytes_limit": 1000}


def test_flash_attention_train_flops_band_closed_form():
    """The analytic visible-pair count matches brute force, windowed and
    causal, and the remat/no-remat matmul multipliers hold their ratio."""
    import numpy as np

    from ddl_tpu.bench.mfu import flash_attention_train_flops

    def brute_pairs(t, w):
        n = 0
        for q in range(t):
            lo = max(0, q - w + 1) if w else 0
            n += q - lo + 1
        return n

    for t, w in ((64, 0), (64, 16), (64, 64), (64, 100), (128, 31)):
        got = flash_attention_train_flops(
            1, 1, t, 1, 1, window=w, accounting="executed"
        )
        want = 9 * 2.0 * brute_pairs(t, w)
        np.testing.assert_allclose(got, want, rtol=1e-12, err_msg=f"{t},{w}")
    # model accounting (MFU): 6 theoretical matmuls, remat-invariant;
    # executed accounting (HFU): 9, +2 under remat replay
    model = flash_attention_train_flops(2, 8, 256, 64, 12)
    assert model == flash_attention_train_flops(2, 8, 256, 64, 12, remat=True)
    ex = flash_attention_train_flops(2, 8, 256, 64, 12, accounting="executed")
    ex_r = flash_attention_train_flops(
        2, 8, 256, 64, 12, remat=True, accounting="executed"
    )
    assert ex / model == 9 / 6 and ex_r / model == 11 / 6
    # banded < causal
    banded = flash_attention_train_flops(2, 8, 256, 64, 12, window=32)
    assert banded < model


def test_chunked_ce_extra_flops_restores_scan_trips():
    """Cost analysis counts a lax.scan body once; the ce_chunk correction
    must bring the loss edge back to full-T FLOPs (VERDICT round 3 #7:
    emitted JSON undercounted chunked rows by the trip count)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from ddl_tpu.bench.mfu import chunked_ce_extra_flops, compiled_step_flops
    from ddl_tpu.ops.losses import fused_chunked_ce

    b, t, d, v, chunk = 2, 64, 64, 256, 16  # 4 scan trips

    def loss(h, w, tgt):
        ce, _ = fused_chunked_ce(h, w, tgt, chunk)
        return ce

    g = jax.grad(loss, argnums=(0, 1))
    h = jnp.zeros((b, t, d), jnp.float32)
    w = jnp.zeros((v, d), jnp.float32)  # vocab-major, as LMHead stores it
    tgt = jnp.zeros((b, t), jnp.int32)
    counted = compiled_step_flops(g, h, w, tgt)
    if not counted > 0:
        import pytest

        pytest.skip("backend has no cost analysis")
    matmul = 2.0 * b * t * d * v
    # the undercount is real: the compiled program reports well under the
    # three model matmuls
    assert counted < 2.5 * matmul
    extra = chunked_ce_extra_flops(b, t, d, v, chunk, accounting="executed")
    # counted-once scan bodies + correction ≈ the four executed matmuls
    # (fwd, checkpoint replay, dx, dW); tolerance covers elementwise work
    np.testing.assert_allclose(counted + extra, 4 * matmul, rtol=0.1)
    # model accounting excludes exactly the checkpoint replay
    delta = extra - chunked_ce_extra_flops(b, t, d, v, chunk)
    np.testing.assert_allclose(delta, matmul, rtol=1e-12)


def test_vocab_chunked_ce_extra_flops_restores_scan_trips():
    """Same counted-once rule for the VOCAB-streamed loss edge: the
    correction must bring the compiled count back to the four executed
    full-V matmuls (fwd, bwd recompute, dx, dW)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from ddl_tpu.bench.mfu import (
        compiled_step_flops,
        vocab_chunked_ce_extra_flops,
    )
    from ddl_tpu.ops.losses import fused_vocab_chunked_ce

    b, t, d, v, vb = 2, 64, 64, 256, 64  # 4 vocab blocks

    def loss(h, w, tgt):
        return fused_vocab_chunked_ce(h, w, tgt, vb)[0]

    g = jax.grad(loss, argnums=(0, 1))
    h = jnp.zeros((b, t, d), jnp.float32)
    w = jnp.zeros((v, d), jnp.float32)
    tgt = jnp.zeros((b, t), jnp.int32)
    counted = compiled_step_flops(g, h, w, tgt)
    if not counted > 0:
        import pytest

        pytest.skip("backend has no cost analysis")
    matmul = 2.0 * b * t * d * v
    assert counted < 2.0 * matmul  # the undercount is real
    extra = vocab_chunked_ce_extra_flops(b, t, d, v, vb,
                                         accounting="executed")
    np.testing.assert_allclose(counted + extra, 4 * matmul, rtol=0.1)
    # model accounting excludes exactly the backward's recompute matmul
    delta = extra - vocab_chunked_ce_extra_flops(b, t, d, v, vb)
    np.testing.assert_allclose(delta, matmul, rtol=1e-12)


def test_fused_dense_block_train_flops_closed_form():
    """The fused-block FLOPs correction (Pallas calls report zero to
    cost analysis): model convention counts 3x (fwd + dW + dx) of the
    true-width 1x1 and the nine-tap 3x3 per layer of each FUSED block
    only; executed adds the padded width and the backward's forward
    recompute, so executed >= model always."""
    import pytest

    from ddl_tpu.bench.mfu import fused_dense_block_train_flops
    from ddl_tpu.ops.fused_dense_block import block_pad

    # one fused block at image 32 -> stem leaves hw=8: two layers
    batch, g, bn_size, f0 = 2, 4, 2, 8
    bn, s = bn_size * g, 8 * 8
    want = 0.0
    for i in range(2):
        want += 3 * (2 * s * (f0 + i * g) * bn) + 3 * (2 * s * 9 * bn * g)
    want *= batch
    got = fused_dense_block_train_flops(
        batch, 32, (2, 2), g, bn_size, f0, fused_blocks=(0,)
    )
    assert got == want
    # non-fused blocks contribute nothing (XLA counts them itself)
    assert fused_dense_block_train_flops(
        batch, 32, (2, 2), g, bn_size, f0, fused_blocks=()
    ) == 0.0
    ex = fused_dense_block_train_flops(
        batch, 32, (2, 2), g, bn_size, f0, fused_blocks=(0,),
        accounting="executed",
    )
    assert ex > got
    pad0, p_total = block_pad(f0, 2, g)
    assert p_total > f0 + 2 * g  # padding is what makes executed larger
    with pytest.raises(ValueError):
        fused_dense_block_train_flops(
            batch, 32, (2, 2), g, bn_size, f0, (0,), accounting="nope"
        )
