"""The PR-5 diagnosis layer: anomaly-triggered profile capture
(``obs/profiler.py``), serving-side percentiles (``obs/serving.py``),
and the pod-wide cross-host view (``obs/pod.py`` / ``obs pod``).

Unit tier is stdlib-only (fake tracers/clocks, synthetic event
streams); the e2e at the bottom drives a real CPU-JAX training run
where an injected loss spike produces a real ``jax.profiler`` trace
directory and a ``profile_capture`` event with an op digest.
"""

import json
import sys

import numpy as np
import pytest


def _run_main(module, argv):
    old = sys.argv
    sys.argv = [module.__name__] + argv
    try:
        module.main()
    finally:
        sys.argv = old


# ---------------------------------------------------------------------------
# quantile accumulator
# ---------------------------------------------------------------------------


def test_quantile_accumulator_exact_matches_numpy():
    from ddl_tpu.obs.serving import QuantileAccumulator

    rng = np.random.default_rng(0)
    for stream in (
        rng.exponential(2.0, size=500),
        rng.normal(10.0, 3.0, size=37),
        np.array([4.2]),
        np.arange(100.0),
    ):
        acc = QuantileAccumulator(capacity=1000)
        for x in stream:
            acc.add(float(x))
        for q in (0.0, 0.5, 0.95, 0.99, 1.0):
            assert acc.quantile(q) == pytest.approx(
                float(np.quantile(stream, q)), rel=1e-12, abs=1e-12
            ), (q, len(stream))
        assert acc.mean == pytest.approx(float(stream.mean()))
        assert acc.min == float(stream.min())
        assert acc.max == float(stream.max())
        assert acc.count == len(stream)


def test_quantile_accumulator_reservoir_beyond_capacity():
    """Past capacity the reservoir is a uniform sample: bounded memory,
    quantiles within a few percent of exact on a smooth stream, exact
    count/mean/min/max either way."""
    from ddl_tpu.obs.serving import QuantileAccumulator

    rng = np.random.default_rng(1)
    stream = rng.exponential(1.0, size=50_000)
    acc = QuantileAccumulator(capacity=2048)
    for x in stream:
        acc.add(float(x))
    assert acc.count == 50_000
    assert len(acc._values) == 2048
    assert acc.mean == pytest.approx(float(stream.mean()))
    for q in (0.5, 0.95):
        exact = float(np.quantile(stream, q))
        assert acc.quantile(q) == pytest.approx(exact, rel=0.1), q
    # deterministic: the same stream gives the same reservoir
    acc2 = QuantileAccumulator(capacity=2048)
    for x in stream:
        acc2.add(float(x))
    assert acc.quantile(0.95) == acc2.quantile(0.95)


def test_quantile_accumulator_validation():
    from ddl_tpu.obs.serving import QuantileAccumulator

    with pytest.raises(ValueError):
        QuantileAccumulator(capacity=0)
    acc = QuantileAccumulator()
    assert acc.quantile(0.5) is None  # empty stream
    acc.add(1.0)
    with pytest.raises(ValueError):
        acc.quantile(1.5)


# ---------------------------------------------------------------------------
# serving stats over decode events
# ---------------------------------------------------------------------------


def _decode_event(dur, warm=True, **over):
    e = {
        "kind": "decode", "prompt_len": 8, "new_tokens": 16, "batch": 2,
        "dur": dur, "queue_delay": dur / 10, "ttft": dur / 4,
        "tok_per_s": 32 / dur, "warm": warm,
    }
    e.update(over)
    return e


def test_serving_stats_percentiles_exclude_cold():
    from ddl_tpu.obs.serving import ServingStats

    events = [_decode_event(50.0, warm=False)]  # the compile request
    events += [_decode_event(d) for d in (1.0, 2.0, 3.0, 4.0)]
    s = ServingStats.from_events(events).summary()
    assert s["requests"] == 5 and s["cold"] == 1
    assert s["tokens"] == 5 * 32 and s["prompt_tokens"] == 5 * 16
    lat = s["percentiles"]["latency_s"]
    assert lat["count"] == 4
    assert lat["p50"] == pytest.approx(2.5)  # the 50s cold outlier excluded
    assert lat["max"] == 4.0
    assert s["percentiles"]["queue_delay_s"]["p50"] == pytest.approx(0.25)
    assert s["percentiles"]["ttft_s"]["p99"] <= 1.0
    assert s["mean_tok_per_s"] == pytest.approx(
        float(np.mean([32 / d for d in (1.0, 2.0, 3.0, 4.0)]))
    )


def test_summarize_and_render_decode_percentiles(tmp_path, capsys):
    """`obs summarize` renders the p50/p95/p99 table from a stream of
    enriched decode events; `obs diff --fail-slowdown` gates on p95
    latency when both sides carry percentiles."""
    from ddl_tpu import cli
    from ddl_tpu.obs import EventWriter

    def write_job(job, durs):
        w = EventWriter(tmp_path, job, host=0)
        w.emit("decode", **{
            k: v for k, v in _decode_event(30.0, warm=False).items()
            if k != "kind"
        })
        for d in durs:
            w.emit("decode", **{
                k: v for k, v in _decode_event(d).items() if k != "kind"
            })
        w.close()

    write_job("fast", [1.0, 1.1, 1.2, 1.3])
    write_job("slow", [2.6, 2.7, 2.8, 2.9])

    cli.main(["obs", "summarize", "fast", "--log-dir", str(tmp_path)])
    out = capsys.readouterr().out
    assert "decode percentiles" in out
    for metric in ("latency_s", "queue_delay_s", "ttft_s", "tok_per_s"):
        assert metric in out
    assert "p50" in out and "p95" in out and "p99" in out
    assert "1 cold excluded" in out

    # two-job diff renders the percentile delta rows and the gate trips
    # on the >100% latency inflation
    with pytest.raises(SystemExit, match="p95 latency"):
        cli.main([
            "obs", "diff", "fast", "slow", "--log-dir", str(tmp_path),
            "--fail-slowdown", "0.5",
        ])
    out = capsys.readouterr().out
    assert "latency_s:p95" in out

    # within tolerance passes and says which gates ran
    cli.main([
        "obs", "diff", "fast", "fast", "--log-dir", str(tmp_path),
        "--fail-slowdown", "0.5",
    ])
    out = capsys.readouterr().out
    assert "OK" in out and "decode p95 latency" in out

    # a stored baseline round-trips the percentile fields
    cli.main([
        "obs", "baseline", "fast", "--log-dir", str(tmp_path),
        "--out", str(tmp_path / "base.json"),
    ])
    capsys.readouterr()
    stored = json.loads((tmp_path / "base.json").read_text())
    assert stored["summary"]["decode"]["percentiles"]["latency_s"]["p95"]
    with pytest.raises(SystemExit, match="p95 latency"):
        cli.main([
            "obs", "diff", "slow", "--log-dir", str(tmp_path),
            "--baseline", str(tmp_path / "base.json"),
            "--fail-slowdown", "0.5",
        ])


# ---------------------------------------------------------------------------
# trace capturer (fake tracer + clock: no JAX)
# ---------------------------------------------------------------------------


class _FakeTracer:
    def __init__(self):
        self.started = []
        self.stopped = 0
        self.active = False

    def start(self, d):
        assert not self.active, "double start_trace"
        self.active = True
        self.started.append(d)

    def stop(self):
        assert self.active, "stop without start"
        self.active = False
        self.stopped += 1


class _Clock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t


def _capturer(writer, tmp_path, **kw):
    from ddl_tpu.obs.profiler import TraceCapturer

    tracer = _FakeTracer()
    clock = _Clock()
    cap = TraceCapturer(
        writer, tmp_path / "xprof", clock=clock,
        tracer_start=tracer.start, tracer_stop=tracer.stop,
        digest_fn=lambda d: {"total_ms": 1.0, "ops": {"dot": 1.0}},
        **kw,
    )
    return cap, tracer, clock


def test_capturer_window_cooldown_and_cap(tmp_path):
    from ddl_tpu.obs import EventWriter, read_events

    w = EventWriter(tmp_path, "job", host=0)
    cap, tracer, clock = _capturer(
        w, tmp_path, steps=2, max_captures=2, cooldown_s=100.0
    )
    # no trigger: steps pass untraced
    cap.on_step(0)
    assert not tracer.started

    assert cap.trigger("loss_spike", step=0) is True
    # triggers while armed/active are absorbed, not re-armed
    assert cap.trigger("loss_spike", step=0) is False
    cap.on_step(1)  # arms -> starts
    assert len(tracer.started) == 1 and tracer.active
    assert cap.trigger("hbm_growth", step=1) is False
    cap.on_step(2)  # 1 step in window: still tracing
    assert tracer.active
    cap.on_step(3)  # window of 2 complete: stop + emit
    assert not tracer.active and cap.captures == 1

    # cooldown: a fresh trigger inside it is absorbed...
    clock.t = 50.0
    assert cap.trigger("throughput_regression", step=4) is False
    # ...and admitted after it
    clock.t = 150.0
    assert cap.trigger("throughput_regression", step=5) is True
    cap.on_step(6)
    cap.on_step(8)  # deadline passed (6 + 2): closes
    assert cap.captures == 2

    # K-cap: no third capture this run
    clock.t = 1000.0
    assert cap.trigger("loss_spike", step=9) is False
    cap.on_step(10)
    assert len(tracer.started) == 2

    w.close()
    events = read_events(w.path)
    captures = [e for e in events if e["kind"] == "profile_capture"]
    assert len(captures) == 2
    first, second = captures
    assert first["ok"] and first["trigger"] == "loss_spike"
    assert first["trace_dir"] == tracer.started[0]
    assert first["digest"]["ops"] == {"dot": 1.0}
    assert first["steps"] == 2 and first["first_step"] == 1
    # the three absorbed triggers are accounted on the next capture
    assert first["suppressed"] == 2  # armed-dup + active-dup
    assert second["suppressed"] == 1  # the cooldown-absorbed one


def test_capturer_finish_closes_open_window(tmp_path):
    from ddl_tpu.obs import EventWriter, read_events

    w = EventWriter(tmp_path, "job2", host=0)
    cap, tracer, _clock = _capturer(w, tmp_path, steps=5)
    cap.trigger("loss_spike", step=3)
    cap.on_step(4)
    assert tracer.active
    cap.finish()  # run ended inside the window
    assert not tracer.active and cap.captures == 1
    w.close()
    (c,) = [e for e in read_events(w.path) if e["kind"] == "profile_capture"]
    assert c["ok"] and c["trigger"] == "loss_spike"


def test_capturer_capture_now_and_failure_disables(tmp_path):
    from ddl_tpu.obs import EventWriter, read_events
    from ddl_tpu.obs.profiler import TraceCapturer

    w = EventWriter(tmp_path, "job3", host=0)
    cap, tracer, _clock = _capturer(w, tmp_path, steps=2)
    assert cap.capture_now("hung_step", window_s=0.0, step=7) is True
    assert cap.captures == 1 and not tracer.active

    # a tracer that raises must disable the capturer, never propagate
    # (the watchdog thread calls this right before os._exit)
    def boom(d):
        raise RuntimeError("profiler unavailable")

    w2 = EventWriter(tmp_path, "job4", host=0)
    cap2 = TraceCapturer(
        w2, tmp_path / "xprof2", tracer_start=boom, tracer_stop=lambda: None
    )
    assert cap2.capture_now("hung_step") is False
    assert cap2.disabled
    assert cap2.trigger("loss_spike") is False  # stays off
    w2.close()
    (e,) = [
        ev for ev in read_events(w2.path) if ev["kind"] == "profile_capture"
    ]
    assert e["ok"] is False and e["disabled"] is True
    w.close()


def test_watchdog_stall_captures_before_escalation(tmp_path):
    """A hung step has no upcoming step boundary: the watchdog calls
    the capturer's synchronous path when the stall fires, so the trace
    (what the wedged device is executing) exists before any
    escalation ends the process."""
    import time as _time

    from ddl_tpu.obs import EventWriter, Watchdog, read_events

    w = EventWriter(tmp_path, "wd-job", host=0)
    cap, tracer, _clock = _capturer(w, tmp_path, steps=2)
    with Watchdog(w, deadline_s=0.15, interval_s=0.03, capturer=cap) as wd:
        wd.beat(5)
        _time.sleep(0.6)  # the stalled "step"
    w.close()
    events = read_events(w.path)
    assert [e for e in events if e["kind"] == "stall"]
    (c,) = [e for e in events if e["kind"] == "profile_capture"]
    assert c["ok"] and c["trigger"] == "hung_step" and c["step"] == 5
    assert len(tracer.started) == 1 and not tracer.active


def test_capturer_step_hook_tolerates_sync_window(tmp_path):
    """Regression: a capture_now window (deadline_step None) in flight on
    the watchdog thread must not crash a concurrent trainer-thread
    on_step with a TypeError — and the non-blocking paths absorb rather
    than stall when the lock is held."""
    import threading

    from ddl_tpu.obs import EventWriter

    w = EventWriter(tmp_path, "job-race", host=0)
    cap, tracer, _clock = _capturer(w, tmp_path, steps=2)
    cap._active = {"trigger": "hung_step", "trigger_step": 3,
                   "trace_dir": str(tmp_path), "steps": None,
                   "deadline_step": None}
    cap.on_step(4)  # previously: '>=' between int and None
    assert cap._active is not None  # sync window untouched
    cap._active = None

    # lock held elsewhere: trigger/on_step return immediately
    with cap._lock:
        done = []

        def worker():
            assert cap.trigger("loss_spike", step=1) is False
            cap.on_step(2)
            done.append(True)

        t = threading.Thread(target=worker)
        t.start()
        t.join(timeout=5.0)
        assert done, "trainer-thread hooks blocked on the capturer lock"
    assert cap.suppressed == 1
    w.close()


def test_capturer_finish_drops_stale_armed_trigger(tmp_path):
    """A trigger armed on the final step must not leak a capture (with
    the old run's attribution) into a later train() segment."""
    from ddl_tpu.obs import EventWriter

    w = EventWriter(tmp_path, "job-stale", host=0)
    cap, tracer, _clock = _capturer(w, tmp_path, steps=2)
    assert cap.trigger("loss_spike", step=9) is True
    cap.finish()  # run ended before any step boundary
    assert cap._armed is None and cap.suppressed == 1
    cap.on_step(0)  # second segment: nothing starts
    assert not tracer.started
    w.close()


def test_capturer_from_env_scopes_override_dir(tmp_path, monkeypatch):
    """DDL_OBS_PROFILE_DIR is pod-shared (supervisors propagate env):
    the capturer scopes it per host, and relaunched incarnations
    (restart epoch > 0) get their own subdir because the capture
    counter resets per process."""
    import os as _os

    from ddl_tpu.obs import EventWriter
    from ddl_tpu.obs.profiler import capturer_from_env

    w = EventWriter(tmp_path, "job-env", host=2)
    env = {"DDL_OBS_PROFILE": "1", "DDL_OBS_PROFILE_DIR": str(tmp_path / "nas")}
    cap = capturer_from_env(w, tmp_path / "default", env=env)
    assert cap.trace_root == _os.path.join(str(tmp_path / "nas"), "h002")

    env["DDL_RESTART_EPOCH"] = "1"
    cap = capturer_from_env(w, tmp_path / "default", env=env)
    assert cap.trace_root == _os.path.join(
        str(tmp_path / "nas"), "h002", "r1"
    )

    # no override: the per-host default root is used as-is (epoch 0)
    del env["DDL_OBS_PROFILE_DIR"]
    env["DDL_RESTART_EPOCH"] = "0"
    cap = capturer_from_env(w, tmp_path / "default", env=env)
    assert cap.trace_root == str(tmp_path / "default")
    w.close()


def test_anomaly_monitor_arms_capturer(tmp_path):
    from ddl_tpu.obs import AnomalyMonitor, EventWriter

    w = EventWriter(tmp_path, "job5", host=0)
    cap, tracer, _clock = _capturer(w, tmp_path, steps=1)
    mon = AnomalyMonitor(w, capturer=cap)
    for i in range(8):
        mon.observe_period(i, loss=1.0)
    mon.observe_period(8, loss=9.0)  # spike -> trigger
    cap.on_step(9)
    cap.on_step(10)
    assert cap.captures == 1
    # record() (externally-detected anomalies) arms too
    mon2 = AnomalyMonitor(w, capturer=cap)
    mon2.record(3, "nonfinite_loss", value=float("nan"))
    assert cap.suppressed >= 1 or cap._armed is not None
    w.close()


def test_throughput_suppressed_after_recompile():
    """A period that recompiled is neither judged nor admitted to the
    trailing window: a known compile stall must not fire the detector
    (or burn a profile capture), and its depressed steps/s must not
    drag the baseline."""
    from ddl_tpu.obs import AnomalyMonitor, ThroughputRegressionDetector

    det = ThroughputRegressionDetector(window=10, drop=0.3, min_points=5)
    for _ in range(8):
        assert det.observe(100.0) is None
    # the compile-stalled period would trip the detector...
    assert det.observe(10.0, suppress=True) is None
    assert det.suppressed == 1
    # ...and did not contaminate the baseline for the next real one
    a = det.observe(10.0)
    assert a and a["baseline"] == pytest.approx(100.0)

    # monitor plumbing: compiles > 0 suppresses only the throughput leg
    mon = AnomalyMonitor()
    for i in range(8):
        mon.observe_period(i, loss=1.0, steps_per_sec=100.0)
    found = mon.observe_period(8, loss=9.0, steps_per_sec=10.0, compiles=1)
    assert {a["type"] for a in found} == {"loss_spike"}
    found = mon.observe_period(9, loss=1.0, steps_per_sec=10.0)
    assert {a["type"] for a in found} == {"throughput_regression"}


# ---------------------------------------------------------------------------
# pod-wide aggregation (synthetic 3-host streams)
# ---------------------------------------------------------------------------


def _write_host_stream(
    log_dir, job, host, periods=4, step_s=0.10, wait_s=0.02
):
    """One host's synthetic stream: period events with a phase breakdown
    plus a barrier event and one anomaly on host 0."""
    from ddl_tpu.obs import EventWriter

    w = EventWriter(log_dir, job, host=host, run_id=f"r{host}")
    for p in range(periods):
        steps = 10
        elapsed = (step_s + wait_s) * steps + 0.01
        w.emit(
            "period", step=p, period=p, steps=steps, elapsed=elapsed,
            steps_per_sec=steps / elapsed,
            phases={
                "step": step_s * steps, "data_wait": wait_s * steps,
                "fence": 0.001,
            },
        )
    w.emit("coord_barrier", name="start", wait=0.5 * (host + 1))
    if host == 0:
        w.emit("anomaly", step=2, type="loss_spike", value=9.9)
        w.emit(
            "profile_capture", step=2, ok=True, trigger="loss_spike",
            trace_dir="/tmp/x", digest={"ops": {"dot": 1.0}, "top_op": "dot.3"},
        )
    w.close()


def test_obs_pod_straggler_and_barriers(tmp_path, capsys):
    from ddl_tpu import cli
    from ddl_tpu.obs.pod import load_pod, pod_summary, render_pod_summary

    job = "pod-job"
    # host 1 is the injected straggler: 2x step time, extra data_wait
    _write_host_stream(tmp_path, job, 0)
    _write_host_stream(tmp_path, job, 1, step_s=0.20, wait_s=0.05)
    _write_host_stream(tmp_path, job, 2)

    streams = load_pod(tmp_path, job)
    assert sorted(streams) == [0, 1, 2]
    s = pod_summary(streams)
    assert s["shared_periods"] == 4
    assert s["straggler"] is not None and s["straggler"]["host"] == 1
    assert s["straggler"]["ratio"] > 1.5
    assert s["skew"][1]["step_s"] == pytest.approx(2.0, rel=0.01)
    # barrier attribution: per-host waits recorded under the name
    assert s["barriers"]["start"][2] == pytest.approx(1.5)
    assert s["hosts"][0]["anomalies"] == 1
    assert s["hosts"][0]["captures"] == 1

    text = render_pod_summary(s, job)
    assert "<-- straggler" in text
    straggler_line = next(
        ln for ln in text.splitlines() if "<-- straggler" in ln
    )
    assert straggler_line.startswith("h1")
    assert "barrier waits" in text
    assert "profile_capture:loss_spike" in text  # on the timeline

    # the CLI end of it
    cli.main(["obs", "pod", job, "--log-dir", str(tmp_path)])
    out = capsys.readouterr().out
    assert "straggler: h1" in out
    assert "timeline" in out
    cli.main(["obs", "pod", job, "--log-dir", str(tmp_path), "--json"])
    parsed = json.loads(capsys.readouterr().out)
    assert parsed["straggler"]["host"] == 1

    with pytest.raises(SystemExit, match="no events"):
        cli.main(["obs", "pod", "nosuch", "--log-dir", str(tmp_path)])


def test_obs_pod_no_straggler_on_balanced_pod(tmp_path):
    from ddl_tpu.obs.pod import load_pod, pod_summary, render_pod_summary

    job = "balanced"
    for h in range(3):
        _write_host_stream(tmp_path, job, h)
    s = pod_summary(load_pod(tmp_path, job))
    assert s["straggler"] is None
    assert "no straggler" in render_pod_summary(s, job)


# ---------------------------------------------------------------------------
# run-scoped rendezvous (launch-token subdirs)
# ---------------------------------------------------------------------------


def test_acquire_launch_scopes_and_refuses_closed(tmp_path):
    from ddl_tpu.coord import Rendezvous, acquire_launch, active_launch_root

    # first launch: all hosts of a fresh pod join the same subdir
    a0 = acquire_launch(tmp_path)
    a1 = acquire_launch(tmp_path)
    assert a0 == a1 == tmp_path / "launches" / "L0001"

    # a completed launch is closed: the next acquire opens a NEW subdir
    # (a lone relaunched host cannot rejoin the finished run's barriers)
    rv = Rendezvous(a0, 0, 2, timeout_s=1.0)
    rv.arrive("start")  # the stale marker the scoping defuses
    rv.mark_finished(0)
    b0 = acquire_launch(tmp_path)
    assert b0 == tmp_path / "launches" / "L0002"
    assert not (b0 / "barriers").exists()  # fresh marker space

    # an aborted launch counts as closed too
    rv2 = Rendezvous(b0, 0, 2, timeout_s=1.0)
    rv2.abort("boom", 1)
    assert acquire_launch(tmp_path) == tmp_path / "launches" / "L0003"

    # an UNfinished launch is joined as-is (crashed-pod relaunch keeps
    # its documented fresh-dir semantics)
    assert acquire_launch(tmp_path) == tmp_path / "launches" / "L0003"

    # explicit operator token pins the subdir
    t = acquire_launch(tmp_path, token="job-incarnation-7")
    assert t == tmp_path / "launches" / "t-job-incarnation-7"
    assert acquire_launch(tmp_path, token="job-incarnation-7") == t

    # a stale token naming a CLOSED launch is refused loudly — a lone
    # host relaunched with the finished run's DDL_LAUNCH_TOKEN must not
    # re-enter its fully-arrived barriers
    Rendezvous(t, 0, 2, timeout_s=1.0).mark_finished(0)
    with pytest.raises(RuntimeError, match="finished/aborted"):
        acquire_launch(tmp_path, token="job-incarnation-7")

    assert active_launch_root(tmp_path) is not None
    assert active_launch_root(tmp_path / "nothing") is None


def test_mark_finished_first_writer_wins(tmp_path):
    from ddl_tpu.coord import Rendezvous

    rv0 = Rendezvous(tmp_path, 0, 2, timeout_s=1.0)
    rv1 = Rendezvous(tmp_path, 1, 2, timeout_s=1.0)
    first = rv0.mark_finished(0)
    second = rv1.mark_finished(3, reason="late")
    assert second == first and first["host"] == 0 and first["rc"] == 0


# ---------------------------------------------------------------------------
# fault injection: the finite loss-spike kind
# ---------------------------------------------------------------------------


def test_spike_fault_poisons_loss_finitely():
    from ddl_tpu.utils import faultinject

    inj = faultinject.activate("spike@step:3:100")
    try:
        for step in range(3):
            faultinject.check_step(step)
        assert faultinject.poison_loss({"loss": 2.0})["loss"] == 2.0
        faultinject.check_step(3)
        poisoned = faultinject.poison_loss({"loss": 2.0})
        assert poisoned["loss"] == pytest.approx(200.0)
        assert np.isfinite(poisoned["loss"])
        # consumed: later periods run clean
        faultinject.check_step(4)
        assert faultinject.poison_loss({"loss": 2.0})["loss"] == 2.0
        assert inj.log == [("spike", "step", 3)]
    finally:
        faultinject.deactivate()


# ---------------------------------------------------------------------------
# e2e: injected loss spike -> one rate-limited jax.profiler capture
# ---------------------------------------------------------------------------


def test_spike_triggers_one_profile_capture_e2e(tmp_path, monkeypatch):
    """The acceptance scenario on CPU JAX: a DDL_FAULT-induced loss
    spike fires the loss-spike detector, which arms the capturer; the
    next steps run under a REAL ``jax.profiler`` trace; exactly one
    ``profile_capture`` event lands, carrying an existing trace dir and
    an xprof op digest."""
    import examples.train_lm as train_lm

    from ddl_tpu.obs import read_events
    from ddl_tpu.obs.events import events_path
    from ddl_tpu.utils import faultinject

    log_dir = tmp_path / "logs"
    monkeypatch.setenv("DDL_FAULT", "spike@step:6")
    monkeypatch.setenv("DDL_OBS_PROFILE", "1")
    monkeypatch.setenv("DDL_OBS_PROFILE_STEPS", "2")
    monkeypatch.setenv("DDL_OBS_PROFILE_MAX", "1")
    faultinject.deactivate()  # re-read DDL_FAULT in this process
    try:
        _run_main(train_lm, [
            "--steps", "12", "--log-every", "1", "--batch", "4",
            "--seq-len", "16", "--d-model", "32", "--layers", "2",
            "--log-dir", str(log_dir), "--job-id", "lm-spike",
            "--no-halt-on-nan",
        ])
    finally:
        faultinject.deactivate()
    events = read_events(events_path(log_dir, "lm-spike", 0))
    spikes = [
        e for e in events
        if e["kind"] == "anomaly" and e.get("type") == "loss_spike"
    ]
    # the anomaly is stamped with the period's boundary index (the
    # spiked step 6 lives in the period whose boundary is step 7)
    assert len(spikes) == 1 and spikes[0]["step"] == 7, spikes
    captures = [e for e in events if e["kind"] == "profile_capture"]
    assert len(captures) == 1, captures  # rate-limited to exactly one
    (cap,) = captures
    assert cap["ok"] is True and cap["trigger"] == "loss_spike"
    import glob
    import os

    assert os.path.isdir(cap["trace_dir"])
    assert glob.glob(
        os.path.join(cap["trace_dir"], "**", "*.xplane.pb"), recursive=True
    ), "no xplane.pb written"
    digest = cap["digest"]
    assert digest and "error" not in digest
    assert digest["ops"], digest  # a non-empty per-op-category breakdown
    assert digest["total_ms"] > 0

    # `obs summarize` surfaces the capture with its digest
    from ddl_tpu import cli

    cli.main(["obs", "summarize", "lm-spike", "--log-dir", str(log_dir)])
