"""Per-tenant SLO engine (obs/slo.py) + the tenant attribution layer
it evaluates: burn-rate math units, the "default" tenant for untagged
and falsy-tagged events, config loading precedence, a multi-tenant
end-to-end fold asserting each class's percentiles land in ITS OWN
digest, and the ``obs diff --fail-slo-burn`` CI gate's exit behavior.
"""

import json
import math

import pytest

from ddl_tpu.obs.slo import (
    DEFAULT_SLO,
    alert_level,
    burn_rate,
    evaluate_slo,
    load_slo,
    render_slo,
)

# ---------------------------------------------------------------------------
# synthetic streams (same layout the fold tests use)
# ---------------------------------------------------------------------------


def _ev(host, kind, ts, **kw):
    e = {
        "ts": ts, "mono": ts, "run": f"r{host}", "host": host,
        "step": kw.pop("step", None), "kind": kind,
    }
    e.update(kw)
    return e


def _append(log_dir, job, host, events):
    d = log_dir / "by_job_id" / job
    d.mkdir(parents=True, exist_ok=True)
    with open(d / f"events-h{host:03d}.jsonl", "a") as f:
        for e in events:
            f.write(json.dumps(e) + "\n")


def _serve_events(host, reqs):
    """``reqs``: (tenant_kw, latency, ttft, shed) tuples — an admit per
    request, a tagged decode + retire for served ones, a shed for the
    rest."""
    evs = [_ev(host, "run_start", 1.0, family="lm")]
    t = 10.0
    for i, (tags, lat, ttft, shed) in enumerate(reqs):
        if shed:
            # the engine sheds at offer time: no admit precedes it
            evs.append(_ev(
                host, "serve_shed", t + 0.01, request_id=i,
                reason="queue_full", **tags,
            ))
        else:
            evs.append(_ev(host, "serve_admit", t, request_id=i, **tags))
            evs.append(_ev(
                host, "decode", t + lat, prompt_len=8, new_tokens=16,
                batch=1, dur=lat, queue_delay=0.0, ttft=ttft,
                tok_per_s=16.0 / lat, warm=True, chips=1, **tags,
            ))
            evs.append(_ev(
                host, "serve_retire", t + lat + 0.01, request_id=i,
                **tags,
            ))
        t += 1.0
    evs.append(_ev(host, "run_end", t + 1.0, phases={}, anomalies=0))
    return evs


# ---------------------------------------------------------------------------
# burn-rate + alert math
# ---------------------------------------------------------------------------


def test_burn_rate_units():
    # 1.0 = spending exactly the budget; linear in the error rate
    assert burn_rate(0.01, 0.01) == 1.0
    assert burn_rate(0.02, 0.01) == 2.0
    assert burn_rate(0.0, 0.01) == 0.0
    # negative error rates clamp (defensive: modeled rates)
    assert burn_rate(-0.5, 0.01) == 0.0
    # a zero budget burns infinitely fast the moment anything errors,
    # but a clean run against a zero budget is NOT on fire
    assert burn_rate(0.5, 0.0) == float("inf")
    assert burn_rate(0.0, 0.0) == 0.0


def test_alert_level_windows():
    alerts = {"page_fast_burn": 14.4, "ticket_slow_burn": 2.0}
    # page needs the fast window to spike AND the slow to confirm
    assert alert_level(20.0, 1.5, alerts) == "page"
    assert alert_level(20.0, 0.5, alerts) == "ok"
    assert alert_level(1.0, 3.0, alerts) == "ticket"
    assert alert_level(0.0, 0.0, alerts) == "ok"
    # no fast window (single incarnation): slow stands in, so a
    # cumulative catastrophe still pages
    assert alert_level(None, 20.0, alerts) == "page"
    assert alert_level(None, None, alerts) == "ok"
    assert alert_level(float("inf"), float("inf"), alerts) == "page"


def test_tenant_of_falsy_tags_fold_to_default():
    """Absent, None, and empty-string tenant tags all normalize to the
    "default" tenant — mixed pre-tenant/tenant streams fold into one
    account instead of splitting on a falsy variant."""
    from ddl_tpu.obs.serving import tenant_of

    assert tenant_of({}) == "default"
    assert tenant_of({"tenant": None}) == "default"
    assert tenant_of({"tenant": ""}) == "default"
    assert tenant_of({"tenant": 0}) == "default"
    assert tenant_of({"tenant": "acme"}) == "acme"


# ---------------------------------------------------------------------------
# config loading
# ---------------------------------------------------------------------------


def test_load_slo_precedence_and_backfill(tmp_path):
    # no config anywhere: a deep copy of the defaults (mutating the
    # result must not poison later loads)
    cfg = load_slo(tmp_path, "nope")
    assert cfg == DEFAULT_SLO and cfg is not DEFAULT_SLO
    cfg["classes"]["interactive"]["availability"] = 0.0
    assert DEFAULT_SLO["classes"]["interactive"]["availability"] == 0.999

    # job-dir slo.json wins over defaults; missing top-level keys
    # backfill so a config may declare only its classes
    job_dir = tmp_path / "by_job_id" / "j"
    job_dir.mkdir(parents=True)
    (job_dir / "slo.json").write_text(json.dumps(
        {"classes": {"gold": {"availability": 0.9999}}}
    ))
    cfg = load_slo(tmp_path, "j")
    assert cfg["classes"] == {"gold": {"availability": 0.9999}}
    assert cfg["default_class"] == "batch"  # backfilled
    assert cfg["alerts"]["page_fast_burn"] == 14.4

    # an explicit --slo path wins over the job dir's file
    other = tmp_path / "override.json"
    other.write_text(json.dumps(
        {"classes": {"silver": {"availability": 0.5}},
         "default_class": "silver"}
    ))
    cfg = load_slo(tmp_path, "j", path=str(other))
    assert "silver" in cfg["classes"] and "gold" not in cfg["classes"]


# ---------------------------------------------------------------------------
# end-to-end: the fold's per-tenant account -> budgets
# ---------------------------------------------------------------------------

ACME = {"tenant": "acme", "priority_class": "interactive"}
BULK = {"tenant": "bulk", "priority_class": "batch"}


def test_multitenant_percentiles_land_in_own_digest(tmp_path):
    """Two tenants with deliberately separated latency distributions:
    each class's percentiles must come from ITS OWN digest — the
    tail-heavy batch tenant cannot leak into interactive's p99, and
    untagged requests land in "default", not in either tenant."""
    from ddl_tpu.obs.fold import fold_job

    reqs = (
        [(ACME, 0.010 + 0.001 * i, 0.002, False) for i in range(8)]
        + [(BULK, 5.0 + i, 0.5, False) for i in range(4)]
        + [({}, 0.5, 0.05, False)]  # untagged -> "default"
    )
    _append(tmp_path, "mt", 0, _serve_events(0, reqs))
    fold = fold_job(tmp_path, "mt", cache=False)
    stats = fold.serving()
    assert sorted(stats.tenants) == ["acme", "bulk", "default"]
    acme = stats.tenants["acme"]
    bulk = stats.tenants["bulk"]
    assert acme["class"] == "interactive" and bulk["class"] == "batch"
    assert acme["requests"] == 8 and bulk["requests"] == 4
    assert stats.tenants["default"]["requests"] == 1
    # separation: interactive's whole distribution sits below 0.02s,
    # batch's above 5s — cross-leaks would drag either p99 across
    assert acme["acc"]["latency_s"].quantile(0.99) < 0.02
    assert bulk["acc"]["latency_s"].quantile(0.99) >= 5.0

    cfg = load_slo()  # defaults: interactive p99_latency_s 2.0
    rep = evaluate_slo(fold, cfg)
    assert sorted(rep["tenants"]) == ["acme", "bulk", "default"]
    a_obj = rep["tenants"]["acme"]["objectives"]
    # every interactive latency sits far under target: zero burn
    assert a_obj["p99_latency_s"]["burn"] == 0.0
    assert a_obj["p99_ttft_s"]["burn"] == 0.0
    assert a_obj["availability"]["burn"] == 0.0
    # batch p99 target is 30s and its latencies top out near 8s
    assert rep["tenants"]["bulk"]["objectives"]["p99_latency_s"]["burn"] == 0.0
    assert rep["alert"] == "ok" and rep["worst_burn"] == 0.0
    # untagged requests got the default class ("batch") budgets
    assert rep["tenants"]["default"]["class"] == "batch"

    # the renderer shows every tenant block
    text = render_slo(rep, "mt")
    for t in ("acme", "bulk", "default"):
        assert f"tenant {t} " in text


def test_latency_budget_burns_when_tail_crosses_target(tmp_path):
    """Half of interactive's requests over the 2s p99 target: the
    over-rate (~0.5) against the 1% budget is a ~50x burn, and the
    cumulative alert escalates to ticket."""
    from ddl_tpu.obs.fold import fold_job

    reqs = (
        [(ACME, 0.01, 0.001, False) for _ in range(4)]
        + [(ACME, 10.0, 3.0, False) for _ in range(4)]
    )
    _append(tmp_path, "burn", 0, _serve_events(0, reqs))
    fold = fold_job(tmp_path, "burn", cache=False)
    rep = evaluate_slo(fold, load_slo())
    obj = rep["tenants"]["acme"]["objectives"]["p99_latency_s"]
    assert obj["over_rate"] == pytest.approx(0.5)
    assert obj["burn"] == pytest.approx(50.0)
    # ttft budget (0.5s) burns too: same 50% over-rate
    assert rep["tenants"]["acme"]["objectives"]["p99_ttft_s"][
        "burn"
    ] == pytest.approx(50.0)
    assert rep["tenants"]["acme"]["alert"] == "ticket"
    assert rep["worst_burn"] == pytest.approx(50.0)


def test_availability_burn_and_fast_window(tmp_path):
    """Sheds burn the availability budget: 2 sheds in 8 offered against
    a 0.9 target is a 2.5x burn; the fast window (newest incarnation's
    per-repoch split) sees the same rate in a single-epoch job, and a
    zero-shed tenant burns nothing."""
    from ddl_tpu.obs.fold import fold_job

    best = {"tenant": "scav", "priority_class": "best_effort"}
    reqs = (
        [(best, 0.01, 0.001, False) for _ in range(6)]
        + [(best, 0.0, 0.0, True) for _ in range(2)]
        + [(ACME, 0.01, 0.001, False) for _ in range(4)]
    )
    _append(tmp_path, "avail", 0, _serve_events(0, reqs))
    fold = fold_job(tmp_path, "avail", cache=False)
    rep = evaluate_slo(fold, load_slo())
    scav = rep["tenants"]["scav"]
    assert scav["admits"] == 6 and scav["sheds"] == 2
    obj = scav["objectives"]["availability"]
    assert obj["shed_rate"] == pytest.approx(0.25)
    assert obj["availability"] == pytest.approx(0.75)
    assert obj["burn"] == pytest.approx(0.25 / 0.1)
    assert obj["fast_burn"] is not None and math.isfinite(obj["fast_burn"])
    assert rep["tenants"]["acme"]["objectives"]["availability"]["burn"] == 0.0


def test_fail_slo_burn_gate_exit_codes(tmp_path, capsys):
    """The CI gate end to end through the CLI: a shed-heavy run trips
    ``--fail-slo-burn``, a clean run passes, and a run with no
    per-tenant signal refuses loudly instead of passing silently."""
    from ddl_tpu import cli

    clean = [(ACME, 0.01, 0.001, False) for _ in range(6)]
    shed_heavy = (
        [(ACME, 0.01, 0.001, False)]
        + [(ACME, 0.0, 0.0, True) for _ in range(5)]
    )
    _append(tmp_path, "clean", 0, _serve_events(0, clean))
    _append(tmp_path, "shedy", 0, _serve_events(0, shed_heavy))
    base = tmp_path / "base.json"
    cli.main([
        "obs", "baseline", "clean", "--log-dir", str(tmp_path),
        "--out", str(base),
    ])
    capsys.readouterr()

    # clean run within a generous gate: exit 0, OK line
    cli.main([
        "obs", "diff", "clean", "--log-dir", str(tmp_path),
        "--baseline", str(base), "--fail-slo-burn", "2.0",
    ])
    assert "OK: worst SLO burn" in capsys.readouterr().out

    # shed-heavy run: 5/6 shed against interactive's 0.1% budget
    with pytest.raises(SystemExit, match="worst SLO burn"):
        cli.main([
            "obs", "diff", "shedy", "--log-dir", str(tmp_path),
            "--baseline", str(base), "--fail-slo-burn", "2.0",
        ])
    capsys.readouterr()

    # no serving data at all: the gate must refuse, not silently pass
    _append(tmp_path, "noserve", 0, [
        _ev(0, "run_start", 1.0, family="lm"),
        _ev(0, "run_end", 2.0, phases={}, anomalies=0),
    ])
    with pytest.raises(SystemExit, match="per-tenant serving data"):
        cli.main([
            "obs", "diff", "noserve", "--log-dir", str(tmp_path),
            "--baseline", str(base), "--fail-slo-burn", "2.0",
        ])


def test_slo_cli_renders_and_json(tmp_path, capsys):
    """``obs slo`` end to end: table and ``--json`` agree on the same
    evaluation, and a custom --slo file changes the verdict."""
    from ddl_tpu import cli

    reqs = [(ACME, 0.01, 0.001, False) for _ in range(5)]
    _append(tmp_path, "cli", 0, _serve_events(0, reqs))
    cli.main(["obs", "slo", "cli", "--log-dir", str(tmp_path)])
    out = capsys.readouterr().out
    assert "== slo — cli ==" in out and "tenant acme [interactive]" in out

    cli.main(["obs", "slo", "cli", "--log-dir", str(tmp_path), "--json"])
    rep = json.loads(capsys.readouterr().out)
    assert rep["alert"] == "ok"
    assert rep["tenants"]["acme"]["worst_burn"] == 0.0

    # a hostile budget via --slo: every request now violates ttft
    tight = tmp_path / "tight.json"
    tight.write_text(json.dumps({
        "classes": {"interactive": {"p99_ttft_s": 0.0001}},
    }))
    cli.main([
        "obs", "slo", "cli", "--log-dir", str(tmp_path),
        "--slo", str(tight), "--json",
    ])
    rep = json.loads(capsys.readouterr().out)
    assert rep["tenants"]["acme"]["worst_burn"] == pytest.approx(100.0)


def test_tenant_goodput_split_in_ledger(tmp_path):
    """The goodput ledger's per-tenant account: chip-seconds split by
    tenant, availability from the serve counters, and the dominant
    badput picker."""
    from ddl_tpu.obs.fold import fold_job
    from ddl_tpu.obs.goodput import ledger_from_fold, tenant_dominant_badput

    reqs = (
        [(ACME, 0.4, 0.01, False) for _ in range(4)]
        + [(BULK, 2.0, 0.1, False) for _ in range(2)]
        + [(BULK, 0.0, 0.0, True)]
    )
    _append(tmp_path, "led", 0, _serve_events(0, reqs))
    ledger = ledger_from_fold(fold_job(tmp_path, "led", cache=False))
    tens = ledger["job"]["tenants"]
    assert sorted(tens) == ["acme", "bulk"]
    assert tens["acme"]["served_s"] == pytest.approx(1.6)
    assert tens["bulk"]["served_s"] == pytest.approx(4.0)
    assert tens["acme"]["availability"] == 1.0
    assert tens["bulk"]["availability"] == pytest.approx(2 / 3)
    # bulk's shed is modeled at its own mean served duration (2.0s)
    assert tens["bulk"]["shed_s"] == pytest.approx(2.0)
    dom = tenant_dominant_badput(tens["bulk"])
    assert dom == ("shed", pytest.approx(2.0))
    assert tenant_dominant_badput(
        {"queued_s": 0.0, "shed_s": 0.0}
    ) is None
