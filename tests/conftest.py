"""Test harness: simulate an 8-device TPU mesh on CPU.

The reference has no automated tests at all (SURVEY.md section 4) — every
distributed path there needs a real NCCL cluster.  Here, every parallelism
strategy is exercised without TPUs by forcing XLA's host platform to expose 8
virtual devices; the same shard_map/pjit programs then run unchanged on a real
TPU slice.
"""

import os

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

# Force the CPU platform even when a TPU plugin was force-registered by the
# environment (config.update wins over a registered-but-uninitialised backend).
jax.config.update("jax_platforms", "cpu")

# Persistent XLA compile cache: the suite is compile-dominated on CPU, and
# caching roughly halves repeat-run wall clock (measured: 17s -> 9.7s for a
# representative pipeline compile).  Set DDL_TEST_COMPILE_CACHE="" to
# disable (e.g. when bisecting compiler issues).
_cache = os.environ.get("DDL_TEST_COMPILE_CACHE", "/tmp/ddl_tpu_test_xla_cache")
if _cache:
    try:
        jax.config.update("jax_compilation_cache_dir", _cache)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    except Exception:
        pass

import pytest  # noqa: E402

# ---------------------------------------------------------------------------
# Fast/slow tiers.  `-m "not slow"` is the core tier (~8 min cold, ~5 min
# with a warm compile cache, vs ~45 min for everything); the slow tier keeps
# the exhaustive parametrizations and end-to-end runs.  Membership is by
# measured duration (>= ~15 s on the dev CPU, 2026-07-30 run) and maintained
# centrally here so test files stay clean — re-measure with
# `pytest --durations=60` when adding heavy tests.
# ---------------------------------------------------------------------------
SLOW_TESTS = (
    "test_cli.py::test_cli_single_end_to_end",
    "test_convert.py::test_real_layout_forward_parity",
    "test_dropout.py::test_lm_interleaved_dropout_deterministic",
    "test_dropout.py::test_lm_pipeline_dropout_deterministic",
    "test_dropout.py::test_vit_pipeline_dropout_runs",
    "test_flash_attention.py::test_lm_flash_matches_dense_model",
    "test_grad_stats.py::",
    "test_lm_checkpoint.py::test_lm_restore_onto_different_mesh",
    "test_lm_checkpoint.py::test_lm_resume_matches_uninterrupted",
    "test_lm_pipeline.py::test_lm_pipeline_1f1b_matches_gpipe",
    "test_lm_pipeline.py::test_lm_pipeline_interleaved_1f1b",
    "test_lm_pipeline.py::test_lm_pipeline_checkpoint_interop",
    "test_lm_pipeline.py::test_lm_pipeline_flash_attention",
    "test_lm_pipeline.py::test_lm_pipeline_interleaved_checkpoint_interop",
    "test_lm_pipeline.py::test_lm_pipeline_interleaved_matches_single",
    "test_lm_pipeline.py::test_lm_pipeline_matches_single_dense",
    "test_lm_pipeline.py::test_lm_pipeline_moe_composition",
    "test_lm_pipeline.py::test_lm_pipeline_with_sequence_parallel_attention",
    "test_lm_pipeline.py::test_lm_pipeline_zb_matches_gpipe_and_1f1b",
    "test_vit.py::test_pipeline_zb_matches_gpipe_and_1f1b",
    "test_misc.py::TestGraftEntry::",
    "test_multihost.py::",
    "test_observability.py::test_train_lm_corpus_eval_writes_val_metrics",
    "test_observability.py::test_train_vit_writes_metric_csvs",
    "test_parallel.py::test_1f1b_matches_gpipe",
    "test_parallel.py::test_dp_matches_single",
    "test_parallel.py::test_pipeline_matches_sequential",
    "test_parallel.py::test_pipeline_remat_matches_no_remat",
    "test_parallel.py::test_strategies_learn",
    "test_pipeline_deep.py::",
    "test_preemption.py::test_sigterm_mid_training_checkpoints_and_resumes",
    "test_serve.py::test_engine_matches_sequential_decode",
    "test_serve.py::test_engine_matches_sequential_variants",
    "test_serve.py::test_shed_under_pressure_e2e",
    "test_serve_prefix.py::test_shared_prefix_bit_identical",
    "test_serve_prefix.py::test_int8_prefix_reuse_within_tolerance",
    "test_serve_prefix.py::test_chunked_prefill_interleaves_decode",
    "test_serve_prefix.py::test_serve_bench_scenario_cli",
    "test_trainer.py::test_resume_from_snapshot",
    "test_trainer.py::test_trainer_end_to_end",
    "test_transformer.py::TestLearning::test_remat_policy_invariance",
    "test_transformer.py::TestStrategyEquivalence::test_fsdp_matches_unsharded",
    "test_transformer.py::TestStrategyEquivalence::test_moe_ep_matches_single",
    "test_transformer.py::TestStrategyEquivalence::test_tp_sp_matches_single",
    "test_vit.py::test_pipeline_1f1b_matches_gpipe",
    "test_vit.py::test_pipeline_interleaved_matches_single",
    "test_vit.py::test_pipeline_interleaved_1f1b",
)


def pytest_collection_modifyitems(config, items):
    for item in items:
        if any(pat in item.nodeid for pat in SLOW_TESTS):
            item.add_marker(pytest.mark.slow)


@pytest.fixture(scope="session")
def tiny_model_cfg():
    """A miniature DenseNet for fast CPU tests (same code path as densenet121)."""
    from ddl_tpu.config import ModelConfig

    return ModelConfig(
        growth_rate=4,
        block_config=(2, 2),
        num_init_features=8,
        bn_size=2,
        num_classes=5,
        split_blocks=(1,),
        compute_dtype="float32",
        remat=False,
    )
