"""Test harness: simulate an 8-device TPU mesh on CPU.

The reference has no automated tests at all (SURVEY.md section 4) — every
distributed path there needs a real NCCL cluster.  Here, every parallelism
strategy is exercised without TPUs by forcing XLA's host platform to expose 8
virtual devices; the same shard_map/pjit programs then run unchanged on a real
TPU slice.
"""

import os

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

# Force the CPU platform even when a TPU plugin was force-registered by the
# environment (config.update wins over a registered-but-uninitialised backend).
jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


@pytest.fixture(scope="session")
def tiny_model_cfg():
    """A miniature DenseNet for fast CPU tests (same code path as densenet121)."""
    from ddl_tpu.config import ModelConfig

    return ModelConfig(
        growth_rate=4,
        block_config=(2, 2),
        num_init_features=8,
        bn_size=2,
        num_classes=5,
        split_blocks=(1,),
        compute_dtype="float32",
        remat=False,
    )
