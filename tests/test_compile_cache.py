"""Persistent compile cache (utils/compile_cache.py): warm restarts.

Host-tier tests of the activation logic — root precedence (arg > env >
pod-agreed default), the off switch, topology keying, warm/cold entry
counting, hit/miss counters via jax.monitoring, and the compile_cache
obs event.  XLA's own persistence is not under test here (the pod-sim
e2e exercises it via the suite cache); what is under test is that the
launch path points JAX at one agreed, keyed directory and reports the
truth about it.
"""

import jax
import pytest

from ddl_tpu.utils import compile_cache as cc


@pytest.fixture(autouse=True)
def _isolate(monkeypatch):
    """Each test starts deactivated with zeroed counters, and the global
    jax config the module mutates is restored afterwards."""
    monkeypatch.setattr(cc, "_active", None)
    monkeypatch.setattr(
        cc, "_counters",
        {"hits": 0, "misses": 0, "evicted": 0, "evicted_bytes": 0},
    )
    monkeypatch.delenv(cc.ENV_CACHE, raising=False)
    monkeypatch.delenv(cc.ENV_CACHE_MIN_S, raising=False)
    monkeypatch.delenv(cc.ENV_CACHE_MAX_BYTES, raising=False)
    prev_dir = jax.config.jax_compilation_cache_dir
    prev_min = jax.config.jax_persistent_cache_min_compile_time_secs
    yield
    jax.config.update("jax_compilation_cache_dir", prev_dir)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", prev_min)


def test_activation_is_opt_in_and_off_wins(tmp_path, monkeypatch):
    # bare local run: no env, no rendezvous -> stays off
    assert cc.activate_compile_cache() is None
    assert cc.cache_stats() is None
    # the force-disable beats even an explicit root
    for off in ("off", "0", ""):
        monkeypatch.setenv(cc.ENV_CACHE, off)
        assert cc.activate_compile_cache(cache_root=tmp_path) is None


def test_env_activation_keys_by_topology_and_counts_entries(
    tmp_path, monkeypatch
):
    monkeypatch.setenv(cc.ENV_CACHE, str(tmp_path))
    monkeypatch.setenv(cc.ENV_CACHE_MIN_S, "0")
    stats = cc.activate_compile_cache()
    assert stats is not None
    key = cc.topology_key()
    assert key.startswith("cpu-d") and key.endswith(
        f"-p{jax.process_count()}"
    )
    assert stats["key"] == key
    assert stats["dir"] == str(tmp_path / key)
    assert stats["entries_before"] == 0 and stats["warm"] is False
    assert stats["agreed"] is False
    # jax was actually pointed at the keyed dir with the min-compile
    # override
    assert jax.config.jax_compilation_cache_dir == str(tmp_path / key)
    assert jax.config.jax_persistent_cache_min_compile_time_secs == 0.0
    # a second incarnation finding entries reports warm
    (tmp_path / key / "xla_exec_a").write_bytes(b"x")
    (tmp_path / key / "xla_exec_b").write_bytes(b"x")
    stats2 = cc.activate_compile_cache()
    assert stats2["entries_before"] == 2 and stats2["warm"] is True


def test_pod_agreed_default_sits_beside_launches(tmp_path):
    from ddl_tpu.coord import Rendezvous

    # the rendezvous root is coord_dir/launches/<token>; the agreed
    # default must OUTLIVE launches: <coord_dir>/compile_cache
    launch = tmp_path / "pod" / "launches" / "l0"
    rv = Rendezvous(launch, 0, 1)
    stats = cc.activate_compile_cache(rv=rv)
    assert stats is not None and stats["agreed"] is True
    assert stats["dir"] == str(
        tmp_path / "pod" / "compile_cache" / stats["key"]
    )


def test_explicit_root_beats_pod_default(tmp_path):
    from ddl_tpu.coord import Rendezvous

    launch = tmp_path / "pod" / "launches" / "l0"
    rv = Rendezvous(launch, 0, 1)
    stats = cc.activate_compile_cache(rv=rv, cache_root=tmp_path / "mine")
    assert stats["dir"].startswith(str(tmp_path / "mine"))


def test_hit_miss_counters_and_event_emission(tmp_path, monkeypatch):
    monkeypatch.setenv(cc.ENV_CACHE, str(tmp_path))

    class Events:
        def __init__(self):
            self.emitted = []

        def emit(self, kind, **fields):
            self.emitted.append((kind, fields))

    ev = Events()
    stats = cc.activate_compile_cache(events=ev)
    assert stats is not None
    # activation emitted one compile_cache event carrying the stats
    assert ev.emitted and ev.emitted[0][0] == "compile_cache"
    assert ev.emitted[0][1]["warm"] is False
    # the monitoring listener counts persistent-cache hit/miss events
    before = dict(cc._counters)
    try:
        from jax import monitoring

        monitoring.record_event("/jax/compilation_cache/cache_hits")
        monitoring.record_event("/jax/compilation_cache/cache_misses")
    except Exception:
        pytest.skip("jax.monitoring.record_event unavailable")
    live = cc.cache_stats()
    assert live["hits"] == before["hits"] + 1
    assert live["misses"] == before["misses"] + 1
    # re-emission reports the live counters
    cc.emit_cache_event(ev)
    assert ev.emitted[-1][1]["hits"] == live["hits"]


def _entry(root, key, name, size=100, age_s=None):
    """One fake cache entry of ``size`` bytes, optionally backdated."""
    import os
    import time

    p = root / key / name
    p.parent.mkdir(parents=True, exist_ok=True)
    p.write_bytes(b"x" * size)
    if age_s is not None:
        t = time.time() - age_s
        os.utime(p, (t, t))
    return p


def test_eviction_bounds_root_lru_by_mtime(tmp_path):
    # four 100-byte entries across two topology keys, oldest first
    old1 = _entry(tmp_path, "tpu-d8-p2", "a", age_s=4000)
    old2 = _entry(tmp_path, "tpu-d8-p2", "b", age_s=3000)
    new1 = _entry(tmp_path, "cpu-d8-p1", "c", age_s=2000)
    new2 = _entry(tmp_path, "cpu-d8-p1", "d", age_s=1000)
    # no bound configured -> unbounded, nothing touched
    assert cc.evict_to_byte_bound(tmp_path) is None
    assert all(p.exists() for p in (old1, old2, new1, new2))
    # under the bound -> a report, but zero evictions
    res = cc.evict_to_byte_bound(tmp_path, max_bytes=1000)
    assert res == {
        "evicted": 0, "evicted_bytes": 0,
        "total_bytes": 400, "max_bytes": 1000,
    }
    # over the bound -> LRU across keys: exactly the two oldest go
    res = cc.evict_to_byte_bound(tmp_path, max_bytes=250)
    assert res["evicted"] == 2 and res["evicted_bytes"] == 200
    assert res["total_bytes"] == 200
    assert not old1.exists() and not old2.exists()
    assert new1.exists() and new2.exists()
    # the counters accumulate across calls (they ride cache_stats)
    assert cc._counters["evicted"] == 2
    assert cc._counters["evicted_bytes"] == 200


def test_eviction_never_strands_active_keys_fresh_entries(tmp_path):
    # the active key's FRESH entries (this incarnation's warm restart)
    # are held back even when the bound cannot otherwise be met; its
    # stale entries are ordinary LRU fodder
    fresh1 = _entry(tmp_path, "cpu-d8-p1", "fresh1")
    fresh2 = _entry(tmp_path, "cpu-d8-p1", "fresh2")
    stale = _entry(tmp_path, "cpu-d8-p1", "stale", age_s=4000)
    other = _entry(tmp_path, "tpu-d8-p2", "other", age_s=500)
    res = cc.evict_to_byte_bound(
        tmp_path, active_key="cpu-d8-p1", max_bytes=150
    )
    # the stale active entry and the other key's entry were evictable;
    # the two fresh active entries survive even though 200b > 150b
    assert not stale.exists() and not other.exists()
    assert fresh1.exists() and fresh2.exists()
    assert res["evicted"] == 2 and res["total_bytes"] == 200


def test_activation_applies_byte_bound_and_reports_evictions(
    tmp_path, monkeypatch
):
    monkeypatch.setenv(cc.ENV_CACHE, str(tmp_path))
    monkeypatch.setenv(cc.ENV_CACHE_MAX_BYTES, "250")
    key = cc.topology_key()
    kept1 = _entry(tmp_path, key, "warm_a")
    kept2 = _entry(tmp_path, key, "warm_b")
    for n in ("x", "y", "z"):
        _entry(tmp_path, "tpu-d256-p32", n, age_s=4000)

    class Events:
        def __init__(self):
            self.emitted = []

        def emit(self, kind, **fields):
            self.emitted.append((kind, fields))

    ev = Events()
    stats = cc.activate_compile_cache(events=ev)
    # the stale key was evicted to meet the bound; the active key's
    # fresh entries survived, so the restart is STILL warm
    assert kept1.exists() and kept2.exists()
    assert not (tmp_path / "tpu-d256-p32" / "x").exists()
    assert stats["entries_before"] == 2 and stats["warm"] is True
    live = cc.cache_stats()
    assert live["evicted"] == 3 and live["evicted_bytes"] == 300
    # the eviction counters ride the compile_cache obs event
    assert ev.emitted[0][0] == "compile_cache"
    assert ev.emitted[0][1]["evicted"] == 3


def test_bench_enable_stays_always_on(tmp_path, monkeypatch):
    # the historical bench entry point: no env -> default dir, still
    # topology-keyed
    monkeypatch.delenv(cc.ENV_CACHE, raising=False)
    cc.enable_compile_cache(default_dir=str(tmp_path / "bench"))
    stats = cc.cache_stats()
    assert stats is not None
    assert stats["dir"].startswith(str(tmp_path / "bench"))
