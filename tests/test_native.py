"""Native C++ loader core: decode parity with PIL, batch path, fallbacks."""

import numpy as np
import pytest
from PIL import Image

from ddl_tpu import native


@pytest.fixture(scope="module")
def png_dir(tmp_path_factory):
    d = tmp_path_factory.mktemp("pngs")
    rng = np.random.default_rng(0)
    arrays = []
    for i in range(6):
        arr = rng.integers(0, 255, (24, 24, 3)).astype(np.uint8)
        Image.fromarray(arr).save(d / f"img{i}.png")
        arrays.append(arr)
    return d, arrays


needs_native = pytest.mark.skipif(
    not native.native_available(), reason="native loader not built"
)


@needs_native
def test_image_size(png_dir):
    d, _ = png_dir
    assert native.image_size(d / "img0.png") == (24, 24)


@needs_native
def test_batch_decode_matches_pil(png_dir):
    d, arrays = png_dir
    paths = [d / f"img{i}.png" for i in range(6)]
    batch = native.load_batch(paths, 24, 24)
    assert batch is not None and batch.shape == (6, 24, 24, 3)
    for i, arr in enumerate(arrays):
        np.testing.assert_array_equal(batch[i], arr)


@needs_native
def test_grayscale_and_palette_promoted_to_rgb(tmp_path):
    gray = np.arange(0, 255, 255 // 16, dtype=np.uint8)[:16]
    img = np.tile(gray, (16, 1))
    Image.fromarray(img, mode="L").save(tmp_path / "gray.png")
    batch = native.load_batch([tmp_path / "gray.png"], 16, 16)
    assert batch is not None
    np.testing.assert_array_equal(batch[0][..., 0], img)
    np.testing.assert_array_equal(batch[0][..., 0], batch[0][..., 1])


@needs_native
def test_missing_file_fails_cleanly(tmp_path):
    assert native.load_batch([tmp_path / "nope.png"], 8, 8) is None


@needs_native
def test_dataloader_uses_native_path(png_dir, tmp_path):
    from ddl_tpu.data import AptosImageDataset, DataLoader

    d, arrays = png_dir
    with open(tmp_path / "meta.csv", "w") as f:
        f.write("new_id_code,diagnosis\n")
        for i in range(6):
            f.write(f"img{i},{i % 5}\n")
    ds = AptosImageDataset(tmp_path / "meta.csv", d, "new_id_code")
    dl = DataLoader(ds, batch_size=3, shuffle=False, drop_last=True, num_workers=0)
    batches = list(dl)
    assert len(batches) == 2
    images, labels = batches[0]
    assert images.shape == (3, 24, 24, 3)
    # order without shuffle is the identity permutation
    np.testing.assert_array_equal(images[0], arrays[0])
    assert list(labels) == [0, 1, 2]
