"""The shared training loop (train/loop.BaseTrainer): the generic concerns
— NaN watchdog, best-metric snapshot gating, fixed-cadence snapshots,
preemption — tested family-independently with a stub, plus the LM family's
period arithmetic (cadence GCD, boundary mapping)."""

import math

import pytest

from ddl_tpu.train.loop import BaseTrainer
from ddl_tpu.utils.preemption import PreemptionGuard


class _Stub(BaseTrainer):
    period_label = "Epoch"

    def __init__(self, losses, evals=None, *, best_metric=None,
                 best_mode="max", save_best=True, cadence=0):
        self.state = None
        self.job_id = "stub"
        self.logger = None
        self.is_logging_process = True
        self.periods_run = 0
        self.num_periods = len(losses)
        self.halt_on_nan = True
        self.preemption_save = False
        self.profile_dir = None
        self.save_best = save_best
        self.best_metric = best_metric
        self.best_mode = best_mode
        self.best_value = -float("inf") if best_mode == "max" else float("inf")
        self._losses = losses
        self._evals = evals or {}
        self._cadence = cadence
        self.saves = []
        self.waited = False

    def run_period(self, period, guard=None):
        if getattr(self, "request_at", None) == period and guard is not None:
            guard.request()
        return {"loss": self._losses[period]}, 5

    def evaluate_period(self, period):
        return self._evals.get(period)

    def snapshot_due(self, period):
        return bool(self._cadence) and (period + 1) % self._cadence == 0

    def save_snapshot(self, period):
        self.saves.append(period)

    def wait_for_saves(self):
        self.waited = True


def test_nan_watchdog_halts():
    t = _Stub([1.0, float("nan"), 0.5])
    with pytest.raises(RuntimeError, match="Non-finite"):
        t.train()
    assert t.periods_run == 1  # the bad period is not committed


def test_best_metric_gate_min_mode():
    evals = {0: {"val_ppl": 9.0}, 1: {"val_ppl": 11.0}, 2: {"val_ppl": 7.0}}
    t = _Stub([1.0, 1.0, 1.0], evals, best_metric="val_ppl", best_mode="min")
    t.train()
    assert t.saves == [0, 2]  # improvement only; the regression is skipped
    assert t.best_value == 7.0
    assert t.waited


def test_best_metric_gate_max_mode_and_disabled():
    evals = {0: {"qwk": 0.1}, 1: {"qwk": 0.5}, 2: {"qwk": 0.4}}
    t = _Stub([1.0] * 3, evals, best_metric="qwk", best_mode="max")
    t.train()
    assert t.saves == [0, 1]
    t2 = _Stub([1.0] * 3, evals, best_metric="qwk", save_best=False)
    t2.train()
    assert t2.saves == []


def test_fixed_cadence_snapshots():
    t = _Stub([1.0] * 6, cadence=2)
    t.train()
    assert t.saves == [1, 3, 5]


def test_preemption_saves_and_stops():
    t = _Stub([1.0] * 100)
    t.request_at = 2
    with PreemptionGuard() as guard:
        guard_installed = guard
        t.train(guard=guard)
    assert t.periods_run == 3  # periods 0..2 ran, then clean exit
    assert t.saves == [2]
    assert t.waited
    assert guard_installed.requested


def test_lm_period_arithmetic():
    """Period boundaries are the union of the cadences' multiples — each
    cadence fires exactly at its own multiples, and coprime cadences don't
    collapse the window to single steps (round-2 review finding)."""
    from ddl_tpu.train.lm_trainer import LMRunConfig, LMTrainer

    run = LMRunConfig(steps=47, log_every=10, eval_every=7,
                      checkpoint_dir="x", save_every=20)
    t = object.__new__(LMTrainer)  # period math only; no model build
    t.run = run
    bounds = {47}
    for c in (10, 7, 20):
        bounds.update(range(c, 48, c))
    t._boundaries = sorted(bounds)
    t._start_step = 0
    # union, not GCD: gcd(10,7,20)=1 but the windows stay multi-step
    assert t._boundaries == [7, 10, 14, 20, 21, 28, 30, 35, 40, 42, 47]
    assert t._period_bounds(0) == (0, 7)
    assert t._period_bounds(1) == (7, 10)
    assert t._period_bounds(10) == (42, 47)  # final partial window
    # every eval/save multiple is a boundary; eval fires only at its own
    ends = {t._period_bounds(p)[1] for p in range(len(t._boundaries))}
    assert all(m in ends for m in range(7, 47, 7))
    assert 20 in ends and 40 in ends
    assert all(e % 7 == 0 for e in ends if not (e % 7))  # sanity
    # resume mid-stream: the first period starts at the resume step
    t._start_step = 43
    assert t._period_bounds(10) == (43, 47)
    import bisect

    assert bisect.bisect_right(t._boundaries, 42) == 10  # resume cursor
    # logging fires only at log_every multiples (and the final step):
    # eval/save boundaries don't densify the console/CSV cadence
    t._start_step = 0
    logged = {
        t._period_bounds(p)[1]
        for p in range(len(t._boundaries))
        if t.log_due(p)
    }
    assert logged == {10, 20, 30, 40, 47}


def test_moe_capacity_anneal(capsys):
    """The trainer drops capacity_factor to capacity_factor_min once the
    LIVE moe_drop_frac falls under capacity_anneal_drop — one step-fn
    rebuild, train state carried over, training continues."""
    import optax

    from ddl_tpu.models.transformer import LMConfig
    from ddl_tpu.parallel.sharding import LMMeshSpec
    from ddl_tpu.train.lm_trainer import LMRunConfig, LMTrainer

    base = dict(
        vocab_size=256, d_model=32, n_layers=1, n_heads=4, head_dim=8,
        d_ff=64, num_experts=4, expert_top_k=2, moe_group=0,
        compute_dtype="float32", remat=False, capacity_factor=1.5,
        capacity_factor_min=1.0,
    )
    run = LMRunConfig(batch=4, seq_len=16, steps=6, log_every=2,
                      log_dir=None, checkpoint_dir=None)

    # threshold 1.0: any measured drop fraction triggers the anneal at the
    # first period; the remaining periods step the rebuilt cf-1.0 program
    cfg = LMConfig(**base, capacity_anneal_drop=1.0)
    t = LMTrainer(cfg, LMMeshSpec(), optax.adam(1e-3), run)
    step_before = int(t.state.step)
    t.train()
    assert t.cfg.capacity_factor == 1.0
    assert int(t.state.step) == 6 and step_before == 0
    assert "capacity anneal" in capsys.readouterr().out

    # disabled when the target equals the running capacity
    cfg = LMConfig(
        **dict(base, capacity_factor_min=1.5), capacity_anneal_drop=1.0
    )
    t = LMTrainer(cfg, LMMeshSpec(), optax.adam(1e-3), run)
    t.train()
    assert t.cfg.capacity_factor == 1.5
    assert "capacity anneal" not in capsys.readouterr().out
