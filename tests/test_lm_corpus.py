"""Token-corpus pipeline (data/lm_corpus.py).

The LM analog of the image data-path tests: window slicing, host-shard
disjointness, epoch reshuffling, and the step-pure batch mapping that
makes resume continue the token stream exactly.
"""

import numpy as np
import pytest

from ddl_tpu.data.lm_corpus import TokenBatches, TokenCorpus, encode_text_file


@pytest.fixture
def corpus_path(tmp_path):
    toks = np.arange(1000, dtype=np.uint16) % 251
    p = tmp_path / "toks.npy"
    np.save(p, toks)
    return p


def test_encode_text_file_roundtrip(tmp_path):
    raw = bytes(range(256)) * 3
    src = tmp_path / "corpus.txt"
    src.write_bytes(raw)
    out = encode_text_file(src, tmp_path / "corpus.npy")
    toks = np.load(out)
    assert toks.dtype == np.uint8
    np.testing.assert_array_equal(toks, np.frombuffer(raw, np.uint8))


def test_windows_and_shift(corpus_path):
    c = TokenCorpus(corpus_path, seq_len=16)
    assert len(c) == 999 // 16
    inp, tgt = c[3]
    assert inp.shape == tgt.shape == (16,)
    np.testing.assert_array_equal(inp[1:], tgt[:-1])  # shifted by one
    np.testing.assert_array_equal(inp, np.arange(48, 64) % 251)
    assert c.max_token() == 250


def test_rejects_bad_inputs(tmp_path, corpus_path):
    bad = tmp_path / "bad.npy"
    np.save(bad, np.zeros((4, 4), np.float32))
    with pytest.raises(ValueError, match="1-D integer"):
        TokenCorpus(bad, 8)
    with pytest.raises(ValueError, match="too short"):
        TokenCorpus(corpus_path, seq_len=2000)
    with pytest.raises(ValueError, match="fewer than one batch"):
        TokenBatches(TokenCorpus(corpus_path, 16), batch=100)


def test_shards_are_disjoint_and_cover(corpus_path):
    c = TokenCorpus(corpus_path, seq_len=16)
    b0 = TokenBatches(c, batch=4, num_shards=2, shard_rank=0)
    b1 = TokenBatches(c, batch=4, num_shards=2, shard_rank=1)
    i0 = set(map(int, b0.sampler.indices()))
    i1 = set(map(int, b1.sampler.indices()))
    assert not (i0 & i1)
    assert len(i0 | i1) == (len(c) // 2) * 2


def test_split_is_disjoint_tail(corpus_path):
    c = TokenCorpus(corpus_path, seq_len=16)
    train, ev = c.split(0.2)
    assert len(train) + len(ev) == len(c)
    assert len(ev) == max(1, int(len(c) * 0.2))
    # eval view is exactly the tail windows of the corpus
    np.testing.assert_array_equal(ev[0][0], c[len(train)][0])
    np.testing.assert_array_equal(ev[len(ev) - 1][0], c[len(c) - 1][0])
    with pytest.raises(IndexError):
        ev[len(ev)]
    with pytest.raises(ValueError):
        c.split(0.0)
    with pytest.raises(ValueError, match="no training windows"):
        TokenCorpus(corpus_path, seq_len=999).split(0.5)  # 1 window total
    # batches over a view work
    b = TokenBatches(train, batch=4)
    inp, _ = next(iter(b))
    assert inp.shape == (4, 16)


def test_batch_at_is_step_pure_and_epochs_reshuffle(corpus_path):
    c = TokenCorpus(corpus_path, seq_len=16)
    b = TokenBatches(c, batch=4)
    per_epoch = len(b)

    # iterating epoch 0 == batch_at(0..len-1)
    b.set_epoch(0)
    for step, (inp, tgt) in enumerate(iter(b)):
        inp2, tgt2 = b.batch_at(step)
        np.testing.assert_array_equal(inp, inp2)
        np.testing.assert_array_equal(tgt, tgt2)

    # second epoch reshuffles
    first_of_e0 = b.batch_at(0)[0]
    first_of_e1 = b.batch_at(per_epoch)[0]
    assert not np.array_equal(first_of_e0, first_of_e1)

    # step-purity across arbitrary access order (resume anywhere)
    a = b.batch_at(per_epoch + 2)[0]
    _ = b.batch_at(3)
    np.testing.assert_array_equal(a, b.batch_at(per_epoch + 2)[0])


def test_cursor_roundtrip_anchors_same_layout(corpus_path):
    """cursor_state -> anchor_resume on an identical layout is a no-op
    for the trajectory: the anchored instance serves the same batches
    as the original, including across the next epoch boundary."""
    c = TokenCorpus(corpus_path, seq_len=16)
    b = TokenBatches(c, batch=4)
    step = len(b) + 3  # 3 batches into shuffle epoch 1
    cur = b.cursor_state(step)
    assert cur == {"shuffle_epoch": 1, "epoch_pos": 3}

    b2 = TokenBatches(TokenCorpus(corpus_path, seq_len=16), batch=4)
    b2.anchor_resume(step, **cur)
    assert b2.locate(step) == (1, 3)
    for s in (step, step + 1, 2 * len(b) + 1):  # incl. epoch 1 -> 2 cross
        np.testing.assert_array_equal(b.batch_at(s)[0], b2.batch_at(s)[0])


def test_anchor_preserves_shuffle_trajectory_when_layout_changes(
    corpus_path, tmp_path
):
    """The elastic case: a restart whose shard layout changed len(b).
    Plain divmod would restart the shuffle-epoch clock from the new
    length; the persisted anchor keeps the epoch sequence going."""
    c_old = TokenCorpus(corpus_path, seq_len=16)   # 62 windows
    b_old = TokenBatches(c_old, batch=4)           # 15 batches/epoch
    step = 17                                      # epoch 1, pos 2
    cur = b_old.cursor_state(step)
    assert cur == {"shuffle_epoch": 1, "epoch_pos": 2}

    # restart sees a grown corpus: 80 windows -> 20 batches/epoch
    np.save(tmp_path / "grown.npy",
            np.arange(1300, dtype=np.uint16) % 251)
    b_new = TokenBatches(TokenCorpus(tmp_path / "grown.npy", 16), batch=4)
    assert len(b_new) == 20
    b_new.anchor_resume(step, **cur)
    # un-anchored divmod would say (0, 17) — a rewind into epoch 0
    assert divmod(step, len(b_new)) == (0, 17)
    assert b_new.locate(step) == (1, 2)
    # the permutation was reseeded from the PERSISTED epoch
    assert b_new.sampler.epoch == 1
    # epochs advance from the anchor: 18 more batches exhausts epoch 1
    assert b_new.locate(step + 18) == (2, 0)
    # and batch_at at the anchor step is epoch-1's pos-2 batch exactly
    b_ref = TokenBatches(TokenCorpus(tmp_path / "grown.npy", 16), batch=4)
    b_ref.set_epoch(1)
    want = b_ref._materialize(b_ref._indices()[2 * 4 : 3 * 4])
    np.testing.assert_array_equal(b_new.batch_at(step)[0], want[0])
