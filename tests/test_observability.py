"""Observability: the shared MetricLogger CSV suite (reference row
schema, single.py:260-269) AND the structured event stream
(``ddl_tpu/obs/``) — per-step phase spans, watchdog stall dumps,
anomaly detectors, and the ``ddl_tpu obs`` run-inspection CLI.
"""

import sys
import time

import numpy as np


def _run_main(module, argv):
    old = sys.argv
    sys.argv = [module.__name__] + argv
    try:
        module.main()
    finally:
        sys.argv = old


def test_train_lm_writes_metric_csvs(tmp_path, capsys):
    import examples.train_lm as train_lm

    from ddl_tpu.bench.analysis import (
        epoch_time_per_job,
        phase_breakdown_per_job,
        throughput_per_job,
    )
    from ddl_tpu.utils.csv_logger import read_metric_csv

    log_dir = tmp_path / "logs"
    _run_main(train_lm, [
        "--steps", "12", "--batch", "4", "--seq-len", "16",
        "--d-model", "32", "--layers", "2",
        "--log-dir", str(log_dir), "--job-id", "lm-test",
    ])
    job_dir = log_dir / "by_job_id" / "lm-test"
    for metric in ("loss", "ce", "steps_per_sec", "tokens_per_sec", "epoch_time"):
        rows = read_metric_csv(job_dir / f"{metric}.csv")
        assert rows and all(np.isfinite(r["value"]) for r in rows), metric
    # analysis aggregates the LM job like any other
    assert "lm-test" in epoch_time_per_job(log_dir)
    rates = throughput_per_job(log_dir)["lm-test"]
    assert rates["tokens_per_sec"] > 0

    # ---- the same run's structured event stream (ddl_tpu/obs/) ----
    from ddl_tpu.obs import read_events
    from ddl_tpu.obs.events import events_path

    events = read_events(events_path(log_dir, "lm-test", 0))
    kinds = {e["kind"] for e in events}
    assert {"run_start", "span", "period", "run_end"} <= kinds
    # every event carries the shared envelope
    for e in events:
        assert {"ts", "mono", "run", "host", "step", "kind"} <= set(e)

    # per-step phase spans exist for the in-loop phases
    span_names = {e["name"] for e in events if e["kind"] == "span"}
    assert {"data_wait", "step", "fence", "logging"} <= span_names

    periods = [e for e in events if e["kind"] == "period"]
    assert sum(p["steps"] for p in periods) == 12
    for p in periods:
        # in-loop phases can't exceed the measured period wall (eval/
        # checkpoint/logging phases run after it); small slack for timer
        # granularity
        inner = sum(
            p["phases"].get(k, 0.0) for k in ("data_wait", "h2d", "step", "fence")
        )
        assert inner <= p["elapsed"] * 1.05 + 0.05

    # the period events and the CSV rows describe the same measurements
    csv_by_step = {
        r["epoch"]: r["value"] for r in read_metric_csv(job_dir / "window_time.csv")
    }
    for p in periods:
        if p["step"] in csv_by_step:
            assert abs(csv_by_step[p["step"]] - p["elapsed"]) < 1e-6
    sps_by_step = {
        r["epoch"]: r["value"] for r in read_metric_csv(job_dir / "steps_per_sec.csv")
    }
    for p in periods:
        if p["step"] in sps_by_step:
            assert abs(sps_by_step[p["step"]] - p["steps_per_sec"]) < 1e-6

    # bench.analysis reads the event stream alongside the CSVs
    breakdown = phase_breakdown_per_job(log_dir)["lm-test"]
    assert breakdown["step"] > 0 and "data_wait" in breakdown

    # ---- `ddl_tpu obs summarize` renders the run from the events ----
    from ddl_tpu import cli

    capsys.readouterr()
    cli.main(["obs", "summarize", "lm-test", "--log-dir", str(log_dir)])
    out = capsys.readouterr().out
    assert "phase breakdown" in out
    assert "steps: 12" in out
    for name in ("step", "data_wait", "fence"):
        assert name in out
    assert "anomalies (0)" in out

    cli.main(["obs", "tail", "lm-test", "--log-dir", str(log_dir), "-n", "3"])
    out = capsys.readouterr().out
    assert "run_end" in out


def test_event_writer_span_nesting(tmp_path):
    from ddl_tpu.obs import EventWriter, read_events

    w = EventWriter(tmp_path, "job", host=0, run_id="r1")
    with w.span("outer"):
        with w.span("inner", step=4):
            pass
    w.emit("custom", step=3, foo=1.5)
    w.close()
    events = read_events(w.path)
    spans = {e["name"]: e for e in events if e["kind"] == "span"}
    assert spans["inner"]["parent"] == "outer" and spans["inner"]["depth"] == 1
    assert spans["outer"]["parent"] is None and spans["outer"]["depth"] == 0
    assert spans["inner"]["step"] == 4
    assert spans["outer"]["dur"] >= spans["inner"]["dur"] >= 0
    (custom,) = [e for e in events if e["kind"] == "custom"]
    assert custom["step"] == 3 and custom["foo"] == 1.5 and custom["run"] == "r1"


def test_step_span_sampler_one_in_n(tmp_path, monkeypatch):
    """`emit_step_spans` as an integer N emits phase spans for 1-in-N
    steps only; period totals still accumulate every step."""
    from ddl_tpu.obs import EventWriter, StepTrace, read_events

    w = EventWriter(tmp_path, "job", host=0)
    trace = StepTrace(w, emit_step_spans=4)
    trace.begin_period(0)
    for step in range(10):
        with trace.phase("step", step=step):
            pass
    # period-boundary phases are ONE write per period (and the
    # preemption checkpoint span is incident-review gold): never thinned,
    # even though the loop tags them with the boundary step
    with trace.phase("checkpoint", step=7):
        pass
    trace.end_period(0, 0, elapsed=1.0, steps=10)
    w.close()
    events = read_events(w.path)
    spans = [e for e in events if e["kind"] == "span"]
    assert [e["step"] for e in spans if e["name"] == "step"] == [0, 4, 8]
    assert [e["step"] for e in spans if e["name"] == "checkpoint"] == [7]
    (period,) = [e for e in events if e["kind"] == "period"]
    assert period["steps"] == 10  # totals cover every step regardless

    # bool settings keep their round-6 meaning; env parses integers
    assert StepTrace(w, emit_step_spans=False).emit_step_spans == 0
    assert StepTrace(w, emit_step_spans=True).emit_step_spans == 1
    monkeypatch.setenv("DDL_OBS_STEP_SPANS", "100")
    t = StepTrace.create(tmp_path, "job2", "lm", host=0)
    assert t.emit_step_spans == 100
    t.writer.close()
    monkeypatch.setenv("DDL_OBS_STEP_SPANS", "off")
    t = StepTrace.create(tmp_path, "job3", "lm", host=0)
    assert t.emit_step_spans == 0
    t.writer.close()


def test_event_writer_stamps_pod_restart_epoch(tmp_path, monkeypatch):
    from ddl_tpu.obs import EventWriter, read_events

    monkeypatch.setenv("DDL_RESTART_EPOCH", "3")
    w = EventWriter(tmp_path, "job-re", host=0)
    w.emit("heartbeat")
    w.close()
    (e,) = read_events(w.path)
    assert e["repoch"] == 3
    monkeypatch.delenv("DDL_RESTART_EPOCH")
    w = EventWriter(tmp_path, "job-re2", host=0)
    w.emit("heartbeat")
    w.close()
    (e,) = read_events(w.path)
    assert "repoch" not in e  # no noise outside pod mode


def test_watchdog_stall_dumps_stacks(tmp_path):
    from ddl_tpu.obs import EventWriter, Watchdog, read_events

    w = EventWriter(tmp_path, "job", host=0)
    with Watchdog(w, deadline_s=0.15, interval_s=0.03) as wd:
        wd.beat(7)
        time.sleep(0.6)  # the deliberately stalled "step"
    w.close()
    events = read_events(w.path)
    assert any(e["kind"] == "heartbeat" for e in events)
    stalls = [e for e in events if e["kind"] == "stall"]
    assert stalls, "a stalled step must produce a stack-dump event"
    assert len(stalls) == 1, "one dump per stall, not one per poll"
    st = stalls[0]
    assert st["step"] == 7 and st["age"] > 0.15
    # this (stalled) thread's stack is in the dump, showing the sleep
    assert any("time.sleep" in s or "sleep(" in s for s in st["stacks"].values())


def test_watchdog_quiet_while_beating(tmp_path):
    from ddl_tpu.obs import EventWriter, Watchdog, read_events

    w = EventWriter(tmp_path, "job", host=0)
    with Watchdog(w, deadline_s=0.2, interval_s=0.03) as wd:
        for i in range(10):
            wd.beat(i)
            time.sleep(0.03)
    w.close()
    events = read_events(w.path)
    assert not [e for e in events if e["kind"] == "stall"]
    beats = [e for e in events if e["kind"] == "heartbeat"]
    assert beats and beats[-1]["step"] is not None


def test_anomaly_detector_units():
    from ddl_tpu.obs import (
        HBMGrowthDetector,
        LossSpikeDetector,
        ThroughputRegressionDetector,
    )

    spike = LossSpikeDetector(window=10, sigma=4.0, min_points=5)
    assert all(spike.observe(1.0 + 0.01 * i) is None for i in range(8))
    a = spike.observe(5.0)
    assert a and a["type"] == "loss_spike" and a["value"] == 5.0

    reg = ThroughputRegressionDetector(window=10, drop=0.3, min_points=5)
    assert all(reg.observe(100.0) is None for i in range(8))
    assert reg.observe(95.0) is None  # within tolerance
    a = reg.observe(10.0)
    assert a and a["type"] == "throughput_regression"

    hbm = HBMGrowthDetector(window=4, min_growth=0.05)
    assert all(hbm.observe(1e9) is None for _ in range(6))  # flat: fine
    growth = HBMGrowthDetector(window=4, min_growth=0.05)
    vals = [1e9, 1.1e9, 1.2e9, 1.4e9]
    results = [growth.observe(v) for v in vals]
    assert results[-1] and results[-1]["type"] == "hbm_growth"
    assert growth.observe(None) is None  # no stats backend: degrade


def test_anomaly_monitor_emits_events(tmp_path):
    from ddl_tpu.obs import AnomalyMonitor, EventWriter, read_events

    w = EventWriter(tmp_path, "job", host=0)
    mon = AnomalyMonitor(w)
    for i in range(8):
        mon.observe_period(i, loss=1.0, steps_per_sec=50.0)
    found = mon.observe_period(8, loss=9.0, steps_per_sec=5.0)
    assert {a["type"] for a in found} == {
        "loss_spike", "throughput_regression"
    }
    w.close()
    events = read_events(w.path)
    assert len([e for e in events if e["kind"] == "anomaly"]) == 2
    assert len(mon.summary_lines()) == 2


def test_decode_emits_request_events(tmp_path):
    """Per-request decode telemetry: a decode event with tokens/s plus
    the request span with dispatch/wait children."""
    import jax
    import jax.numpy as jnp

    from ddl_tpu.infer import make_lm_generator
    from ddl_tpu.models.transformer import LMConfig, TransformerLM
    from ddl_tpu.obs import EventWriter, read_events

    cfg = LMConfig(
        vocab_size=32, d_model=16, n_layers=2, n_heads=2, head_dim=8,
        d_ff=32, compute_dtype="float32", attn_impl="dense", remat=False,
    )
    import flax.linen as nn

    params = nn.meta.unbox(
        TransformerLM(cfg, None).init(
            jax.random.key(0), jnp.zeros((2, 4), jnp.int32)
        )["params"]
    )
    w = EventWriter(tmp_path, "decode-job", host=0)
    gen = make_lm_generator(
        cfg, prompt_len=4, max_new=3, batch=2, obs=w
    )
    toks = gen(params, jnp.zeros((2, 4), jnp.int32))
    assert toks.shape == (2, 3)
    toks = gen(params, jnp.ones((2, 4), jnp.int32))
    w.close()
    events = read_events(w.path)
    decodes = [e for e in events if e["kind"] == "decode"]
    assert len(decodes) == 2
    for d in decodes:
        assert d["tok_per_s"] > 0 and d["new_tokens"] == 3 and d["batch"] == 2
    spans = [e for e in events if e["kind"] == "span"]
    by_name = {e["name"]: e for e in spans}
    assert by_name["dispatch"]["parent"] == "decode_request"
    assert by_name["wait"]["parent"] == "decode_request"
    assert by_name["decode_request"]["parent"] is None

    # the summary aggregates decode telemetry
    from ddl_tpu.obs.report import load_run, summarize_run

    s = summarize_run(load_run(tmp_path, "decode-job"))
    assert s["decode"]["requests"] == 2
    assert s["decode"]["tokens"] == 12
    assert s["decode"]["mean_tok_per_s"] > 0


def test_train_lm_corpus_eval_writes_val_metrics(tmp_path):
    import examples.train_lm as train_lm

    from ddl_tpu.utils.csv_logger import read_metric_csv

    # tiny corpus: enough windows for a train/eval split at seq-len 16
    corpus = tmp_path / "corpus.npy"
    rng = np.random.default_rng(0)
    np.save(corpus, rng.integers(0, 255, 4096).astype(np.uint16))
    log_dir = tmp_path / "logs"
    _run_main(train_lm, [
        "--steps", "4", "--batch", "4", "--seq-len", "16",
        "--d-model", "32", "--layers", "2",
        "--corpus", str(corpus), "--eval-every", "2", "--eval-frac", "0.2",
        "--log-dir", str(log_dir), "--job-id", "lm-ev",
    ])
    job_dir = log_dir / "by_job_id" / "lm-ev"
    for metric in ("val_loss", "val_ppl"):
        rows = read_metric_csv(job_dir / f"{metric}.csv")
        assert rows and all(np.isfinite(r["value"]) for r in rows), metric


def test_train_vit_writes_metric_csvs(tmp_path):
    import examples.train_vit as train_vit

    from ddl_tpu.bench.analysis import final_epoch_quality, throughput_per_job
    from ddl_tpu.utils.csv_logger import read_metric_csv

    log_dir = tmp_path / "logs"
    _run_main(train_vit, [
        "--epochs", "2", "--batch", "8", "--image-size", "16", "--patch", "4",
        "--d-model", "32", "--layers", "2",
        "--num-train", "24", "--num-test", "13",  # odd test size: padding path
        "--log-dir", str(log_dir), "--job-id", "vit-test",
        "--checkpoint-dir", str(tmp_path / "ckpt"),
    ])
    job_dir = log_dir / "by_job_id" / "vit-test"
    for metric in (
        "loss", "epoch_time", "img_per_sec", "val_loss", "val_accuracy", "qwk"
    ):
        rows = read_metric_csv(job_dir / f"{metric}.csv")
        assert [r["epoch"] for r in rows] == [0, 1], metric
        assert all(np.isfinite(r["value"]) for r in rows), metric
    quality = final_epoch_quality(log_dir)
    assert "val_accuracy" in quality["vit"] or "val_loss" in quality["vit"]
    assert throughput_per_job(log_dir)["vit-test"]["img_per_sec"] > 0

    # event stream: ViT rides the same loop instrumentation (per-step
    # data_wait/h2d/step/fence spans, period events with eval phase)
    from ddl_tpu.obs import read_events
    from ddl_tpu.obs.events import events_path

    events = read_events(events_path(log_dir, "vit-test", 0))
    span_names = {e["name"] for e in events if e["kind"] == "span"}
    assert {"data_wait", "h2d", "step", "fence", "eval"} <= span_names
    periods = [e for e in events if e["kind"] == "period"]
    assert [p["period"] for p in periods] == [0, 1]
    for p in periods:
        assert p["phases"]["step"] > 0
        inner = sum(
            p["phases"].get(k, 0.0)
            for k in ("data_wait", "h2d", "step", "fence")
        )
        assert inner <= p["elapsed"] * 1.05 + 0.05
