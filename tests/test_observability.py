"""LM/ViT observability: the beyond-parity families emit the shared
MetricLogger CSV suite (reference row schema, single.py:260-269) so
``ddl_tpu.bench.analysis`` aggregates all three model families — round 1
left these loops bespoke with zero CSV output (VERDICT round 1, Missing #4).
"""

import sys

import numpy as np


def _run_main(module, argv):
    old = sys.argv
    sys.argv = [module.__name__] + argv
    try:
        module.main()
    finally:
        sys.argv = old


def test_train_lm_writes_metric_csvs(tmp_path):
    import examples.train_lm as train_lm

    from ddl_tpu.bench.analysis import epoch_time_per_job, throughput_per_job
    from ddl_tpu.utils.csv_logger import read_metric_csv

    log_dir = tmp_path / "logs"
    _run_main(train_lm, [
        "--steps", "12", "--batch", "4", "--seq-len", "16",
        "--d-model", "32", "--layers", "2",
        "--log-dir", str(log_dir), "--job-id", "lm-test",
    ])
    job_dir = log_dir / "by_job_id" / "lm-test"
    for metric in ("loss", "ce", "steps_per_sec", "tokens_per_sec", "epoch_time"):
        rows = read_metric_csv(job_dir / f"{metric}.csv")
        assert rows and all(np.isfinite(r["value"]) for r in rows), metric
    # analysis aggregates the LM job like any other
    assert "lm-test" in epoch_time_per_job(log_dir)
    rates = throughput_per_job(log_dir)["lm-test"]
    assert rates["tokens_per_sec"] > 0


def test_train_lm_corpus_eval_writes_val_metrics(tmp_path):
    import examples.train_lm as train_lm

    from ddl_tpu.utils.csv_logger import read_metric_csv

    # tiny corpus: enough windows for a train/eval split at seq-len 16
    corpus = tmp_path / "corpus.npy"
    rng = np.random.default_rng(0)
    np.save(corpus, rng.integers(0, 255, 4096).astype(np.uint16))
    log_dir = tmp_path / "logs"
    _run_main(train_lm, [
        "--steps", "4", "--batch", "4", "--seq-len", "16",
        "--d-model", "32", "--layers", "2",
        "--corpus", str(corpus), "--eval-every", "2", "--eval-frac", "0.2",
        "--log-dir", str(log_dir), "--job-id", "lm-ev",
    ])
    job_dir = log_dir / "by_job_id" / "lm-ev"
    for metric in ("val_loss", "val_ppl"):
        rows = read_metric_csv(job_dir / f"{metric}.csv")
        assert rows and all(np.isfinite(r["value"]) for r in rows), metric


def test_train_vit_writes_metric_csvs(tmp_path):
    import examples.train_vit as train_vit

    from ddl_tpu.bench.analysis import final_epoch_quality, throughput_per_job
    from ddl_tpu.utils.csv_logger import read_metric_csv

    log_dir = tmp_path / "logs"
    _run_main(train_vit, [
        "--epochs", "2", "--batch", "8", "--image-size", "16", "--patch", "4",
        "--d-model", "32", "--layers", "2",
        "--num-train", "24", "--num-test", "13",  # odd test size: padding path
        "--log-dir", str(log_dir), "--job-id", "vit-test",
        "--checkpoint-dir", str(tmp_path / "ckpt"),
    ])
    job_dir = log_dir / "by_job_id" / "vit-test"
    for metric in (
        "loss", "epoch_time", "img_per_sec", "val_loss", "val_accuracy", "qwk"
    ):
        rows = read_metric_csv(job_dir / f"{metric}.csv")
        assert [r["epoch"] for r in rows] == [0, 1], metric
        assert all(np.isfinite(r["value"]) for r in rows), metric
    quality = final_epoch_quality(log_dir)
    assert "val_accuracy" in quality["vit"] or "val_loss" in quality["vit"]
    assert throughput_per_job(log_dir)["vit-test"]["img_per_sec"] > 0
