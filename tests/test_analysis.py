"""Static analysis (`ddl_tpu lint`, ddl_tpu/analysis/): every AST rule
and every sharding-contract violation class, exercised through known-good
/ known-bad fixture modules (tests/lint_fixtures/) plus unit probes —
and the CI gate itself: lint over the shipped package must match the
committed LINT_BASELINE.json exactly.
"""

import json
import shutil
from pathlib import Path

import pytest

from ddl_tpu.analysis.astlint import lint_file, lint_package, load_registry
from ddl_tpu.analysis.findings import (
    Finding,
    load_baseline,
    save_baseline,
    split_by_baseline,
    suppressed,
)

REPO = Path(__file__).resolve().parents[1]
PACKAGE = REPO / "ddl_tpu"
FIXTURES = Path(__file__).parent / "lint_fixtures"
REGISTRY = load_registry(PACKAGE)


def _rules(findings):
    return [f.rule for f in findings]


def _lint_fixture(name):
    return lint_file(FIXTURES / name, REPO, REGISTRY)


# ---------------------------------------------------------------------------
# AST rules: known-bad fixtures
# ---------------------------------------------------------------------------


def test_bad_traced_fixture_every_interop_class():
    fs = _lint_fixture("bad_traced.py")
    by_rule = {}
    for f in fs:
        by_rule.setdefault(f.rule, []).append(f)
    # 3 nondet: time.time, random.random, set iteration
    assert len(by_rule["nondeterminism"]) == 3
    # 6 host-sync: float (x2: loss_fn + sink-flow inner_loss), .item,
    # np.asarray, device_get, block_until_ready
    assert len(by_rule["host-sync"]) == 6
    assert set(by_rule) == {"nondeterminism", "host-sync"}
    # every finding carries a real line in the fixture
    src_lines = (FIXTURES / "bad_traced.py").read_text().splitlines()
    for f in fs:
        assert 1 <= f.line <= len(src_lines)


def test_sink_param_flow_reaches_indirect_loss_fn():
    fs = _lint_fixture("bad_traced.py")
    assert any(
        f.rule == "host-sync" and "inner_loss" in f.message for f in fs
    ), "loss fn handed through a helper into value_and_grad must be traced"


def test_trace_kind_fixture_registered_vs_not():
    """The causal-trace kinds are registered; an unregistered trace-ish
    kind still fails the obs-event rule (LINT_BASELINE.json stays
    empty, so the gate catches it on the spot)."""
    fs = _lint_fixture("bad_trace_kind.py")
    rules = _rules(fs)
    assert rules.count("obs-event-unregistered") == 1
    assert len(fs) == 1
    assert "trace_hop" in fs[0].message


def test_hbm_kind_fixture_registered_vs_not():
    """The HBM-ledger kinds are registered; an unregistered memory-ish
    kind still fails the obs-event rule (LINT_BASELINE.json stays
    empty, so a new memory emitter that skips the registry fails the
    gate on the spot instead of silently vanishing from the ``obs
    hbm`` account)."""
    fs = _lint_fixture("bad_hbm_kind.py")
    rules = _rules(fs)
    assert rules.count("obs-event-unregistered") == 1
    assert len(fs) == 1
    assert "hbm_leak_report" in fs[0].message


def test_tenant_tagged_kind_still_needs_registry():
    """A ``tenant``/``priority_class`` tag rides the registered serving
    kinds as optional fields — it does not exempt an UNREGISTERED kind
    from the obs-event rule (LINT_BASELINE.json stays empty, so a
    tenant-tagged typo'd kind fails the gate on the spot instead of
    silently vanishing from every per-tenant digest)."""
    fs = _lint_fixture("bad_tenant_kind.py")
    rules = _rules(fs)
    assert rules.count("obs-event-unregistered") == 1
    assert len(fs) == 1
    assert "tenant_quota" in fs[0].message


def test_bad_misc_fixture_rules():
    fs = _lint_fixture("bad_misc.py")
    rules = _rules(fs)
    assert rules.count("compat-bypass") == 2  # legacy import + check_rep
    assert rules.count("pspec-unknown-axis") == 1
    assert rules.count("obs-event-unregistered") == 1
    assert rules.count("anomaly-type-unregistered") == 1
    assert rules.count("bare-except") == 1
    assert len(fs) == 6
    bad_axis = next(f for f in fs if f.rule == "pspec-unknown-axis")
    assert "batch_x" in bad_axis.message
    # the module-declared 'ring' mesh axis is allowed
    assert not any("'ring'" in f.message for f in fs)


def test_good_fixture_is_clean():
    assert _lint_fixture("good_module.py") == []


# ---------------------------------------------------------------------------
# AST rules: module-scoped rules (recovery excepts, step-module donation)
# ---------------------------------------------------------------------------


def _lint_tmp(tmp_path, rel, source):
    p = tmp_path / rel
    p.parent.mkdir(parents=True, exist_ok=True)
    p.write_text(source)
    return lint_file(p, tmp_path, REGISTRY)


BROAD_EXCEPT_SRC = """
def load(path):
    try:
        return open(path).read()
    except Exception:
        return None

def load_reraise(path):
    try:
        return open(path).read()
    except Exception as e:
        raise RuntimeError("context") from e
"""


def test_broad_except_flagged_in_recovery_modules_only(tmp_path):
    fs = _lint_tmp(tmp_path, "checkpoint.py", BROAD_EXCEPT_SRC)
    # the swallowing handler is flagged; the re-raising one is not
    assert _rules(fs) == ["broad-except"]
    assert fs[0].line == 5
    assert _lint_tmp(tmp_path, "bench/whatever.py", BROAD_EXCEPT_SRC) == []


def test_donation_rule_in_step_modules(tmp_path):
    src = """
import jax

def make(train_step):
    return jax.jit(train_step, in_shardings=(None,))
"""
    fs = _lint_tmp(tmp_path, "train/steps.py", src)
    assert _rules(fs) == ["donation-missing"]
    ok = src.replace("in_shardings=(None,)",
                     "in_shardings=(None,), donate_argnums=(0,)")
    assert _lint_tmp(tmp_path, "train/steps.py", ok) == []
    # outside the step modules the rule does not apply
    assert _lint_tmp(tmp_path, "bench/lm.py", src) == []


EXIT_NO_INTENT_SRC = """
import os
import sys

def die():
    os._exit(75)

def die_politely(rv):
    rv.publish_intent("crash", 1, 0)
    sys.exit(1)

def hand_off(exit_fn=None):
    fn = exit_fn or os._exit  # the escape-hatch reference counts too
    bail = sys.exit  # a bare sys.exit alias is the same escape hatch
    fn(75)
"""


def test_exit_without_intent_rule_in_coord_paths(tmp_path):
    # the bare call and the passed-around function objects (os._exit
    # AND sys.exit) are flagged; the function that publishes intent
    # first is clean
    for rel in ("supervisor.py", "coord.py", "obs/watchdog.py"):
        fs = _lint_tmp(tmp_path, rel, EXIT_NO_INTENT_SRC)
        assert _rules(fs) == ["exit-without-intent"] * 3, (rel, fs)
        assert {f.line for f in fs} == {6, 13, 14}
    # outside the coordination modules the rule does not apply
    assert _lint_tmp(tmp_path, "bench/lm.py", EXIT_NO_INTENT_SRC) == []
    # suppression works like every other rule
    ok = EXIT_NO_INTENT_SRC.replace(
        "os._exit(75)",
        "os._exit(75)  # ddl-lint: disable=exit-without-intent",
    ).replace(
        "fn = exit_fn or os._exit  # the escape-hatch reference counts too",
        "fn = exit_fn or os._exit  # ddl-lint: disable=exit-without-intent",
    ).replace(
        "bail = sys.exit  # a bare sys.exit alias is the same escape hatch",
        "bail = sys.exit  # ddl-lint: disable=exit-without-intent",
    )
    assert _lint_tmp(tmp_path, "supervisor.py", ok) == []


PSPEC_HAND_ROLLED_SRC = """
from jax.sharding import PartitionSpec as P

from ddl_tpu.parallel.rules import TOKEN_SPEC

BAD = P("data")
ALSO_BAD = P(("data", "expert"), "seq")
OK_EMPTY = P()
OK_NONE = P(None, None)
OK_DERIVED = P(None, *TOKEN_SPEC)
AXIS = "model"
OK_VARIABLE = P(AXIS, None)
"""


def test_pspec_hand_rolled_rule_in_step_factories(tmp_path):
    """Hand-written PartitionSpec axis literals in the step-factory
    modules bypass the rule engine and are flagged; P(), all-None,
    star-derived, and axis-variable specs are fine."""
    for rel in ("train/steps.py", "train/lm_steps.py",
                "train/vit_steps.py"):
        fs = _lint_tmp(tmp_path, rel, PSPEC_HAND_ROLLED_SRC)
        rules = [f.rule for f in fs if f.rule == "pspec-hand-rolled"]
        assert rules == ["pspec-hand-rolled"] * 2, (rel, fs)
        assert any("'data'" in f.message for f in fs)
    # outside the step factories the rule does not apply
    fs = _lint_tmp(tmp_path, "parallel/rules.py", PSPEC_HAND_ROLLED_SRC)
    assert [f.rule for f in fs if f.rule == "pspec-hand-rolled"] == []
    # suppression works like every other rule
    ok = PSPEC_HAND_ROLLED_SRC.replace(
        'BAD = P("data")',
        'BAD = P("data")  # ddl-lint: disable=pspec-hand-rolled',
    ).replace(
        'ALSO_BAD = P(("data", "expert"), "seq")',
        'ALSO_BAD = P(("data", "expert"), "seq")'
        '  # ddl-lint: disable=pspec-hand-rolled',
    )
    fs = _lint_tmp(tmp_path, "train/steps.py", ok)
    assert [f.rule for f in fs if f.rule == "pspec-hand-rolled"] == []


def test_shipped_step_factories_have_no_hand_rolled_pspecs():
    """The refactored factories draw every axis name from the rule
    engine — the package must be clean under the new rule."""
    fs = [
        f for f in lint_package(PACKAGE)
        if f.rule == "pspec-hand-rolled"
    ]
    assert fs == [], "\n".join(f.format() for f in fs)


def test_shipped_watchdog_escalation_publishes_intent():
    """The real watchdog passes the rule because _escalate publishes
    exit intent before its os._exit — delete that call and the linter
    must catch it (proven by the fixture test above)."""
    fs = [
        f for f in lint_package(PACKAGE)
        if f.rule == "exit-without-intent"
    ]
    assert fs == [], "\n".join(f.format() for f in fs)


def test_suppression_comment_silences_one_rule(tmp_path):
    src = """
import jax

def step(x):
    return float(x)  # ddl-lint: disable=host-sync

jax.jit(step)
"""
    assert _lint_tmp(tmp_path, "m.py", src) == []
    # the suppression names a different rule -> finding stays
    other = src.replace("disable=host-sync", "disable=nondeterminism")
    assert _rules(_lint_tmp(tmp_path, "m.py", other)) == ["host-sync"]


def test_suppressed_helper():
    assert suppressed("x = 1  # ddl-lint: disable", "anything")
    assert suppressed("x = 1  # ddl-lint: disable=a,b", "b")
    assert not suppressed("x = 1  # ddl-lint: disable=a", "b")
    assert not suppressed("x = 1  # noqa", "a")


# ---------------------------------------------------------------------------
# baseline mechanics
# ---------------------------------------------------------------------------


def test_baseline_roundtrip_and_split(tmp_path):
    a = Finding("p.py", 3, "host-sync", "m1")
    b = Finding("p.py", 9, "bare-except", "m2")
    c = Finding("q.py", 1, "host-sync", "m3")
    save_baseline(tmp_path / "b.json", [a, b])
    loaded = load_baseline(tmp_path / "b.json")
    assert set(loaded) == {a, b}
    # b was fixed; c is new; a moved lines (still baselined by content)
    moved = Finding("p.py", 30, "host-sync", "m1")
    new, known, stale = split_by_baseline([moved, c], loaded)
    assert new == [c]
    assert known == [moved]
    assert stale == [b]


def test_shipped_package_matches_committed_baseline():
    """The CI gate: AST lint over the shipped package produces exactly
    the findings in LINT_BASELINE.json — new findings fail tier-1, and
    fixed ones must shrink the baseline (--update-baseline)."""
    baseline = load_baseline(REPO / "LINT_BASELINE.json")
    findings = lint_package(PACKAGE)
    new, _known, stale = split_by_baseline(findings, baseline)
    assert new == [], (
        "new lint findings not in LINT_BASELINE.json:\n"
        + "\n".join(f.format() for f in new)
    )
    assert stale == [], (
        "stale baseline entries (fixed findings) — run "
        "`ddl_tpu lint --baseline LINT_BASELINE.json --update-baseline`:\n"
        + "\n".join(f.format() for f in stale)
    )


def test_hlo_baseline_file_matches_probe_registry():
    """Fast half of the compiled-IR gate: HLO_BASELINE.json exists,
    loads, and its program set is exactly what the probe registry
    builds — a renamed or dropped probe fails here in milliseconds,
    before anyone pays for a compile."""
    from ddl_tpu.analysis.hlolint import (
        HLO_PROBES, load_hlo_baseline, probe_names,
    )

    path = REPO / "HLO_BASELINE.json"
    assert path.exists(), (
        "HLO_BASELINE.json missing — run "
        "`ddl_tpu lint --hlo --update-baseline`"
    )
    programs = load_hlo_baseline(path)
    assert programs, "HLO_BASELINE.json has no program inventories"
    for name, data in programs.items():
        assert data["level"] in ("hlo", "stablehlo"), name
        assert "collectives" in data and "fingerprint" in data, name
    # every baselined program belongs to a registered probe family
    # (serve fans out to serve_prefill/serve_decode/serve_chunk)
    families = set(probe_names())
    for name in programs:
        assert name in families or name.rsplit("_", 1)[0] in families, (
            f"baseline program {name!r} matches no registered probe"
        )
    # every registered probe module really exists in the package
    for _name, mod, _build in HLO_PROBES:
        rel = Path(*mod.split(".")).with_suffix(".py")
        assert (REPO / rel).exists(), mod


@pytest.mark.slow
def test_shipped_package_matches_committed_hlo_baseline():
    """The live compiled-IR gate: lower + compile every probe program
    on its simulated mesh and diff the inventories against the
    committed HLO_BASELINE.json — the test-suite twin of the CI step
    `ddl_tpu lint --hlo --hlo-baseline HLO_BASELINE.json`."""
    from ddl_tpu.analysis.contracts import ensure_simulated_mesh
    from ddl_tpu.analysis.hlolint import run_hlo_lint

    ensure_simulated_mesh(8)
    result = run_hlo_lint(baseline_path=REPO / "HLO_BASELINE.json")
    assert result.ok, (
        "compiled-IR drift against HLO_BASELINE.json:\n"
        + "\n".join(f.format() for f in result.findings)
    )


def test_event_registry_covers_package_emits():
    """Every emit(<literal>) in the package names a registered kind —
    the registry rule over the real tree, independent of the baseline."""
    fs = [
        f for f in lint_package(PACKAGE)
        if f.rule in ("obs-event-unregistered", "anomaly-type-unregistered")
    ]
    assert fs == [], "\n".join(f.format() for f in fs)


# ---------------------------------------------------------------------------
# sharding contracts: each violation class at unit level
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def small_mesh():
    from ddl_tpu.parallel.sharding import LMMeshSpec, build_lm_mesh

    return build_lm_mesh(LMMeshSpec(data=2, model=2))


def _probe():
    from ddl_tpu.analysis import contracts
    from ddl_tpu.train.lm_steps import make_lm_step_fns

    return contracts._Probe(make_lm_step_fns)


def test_contract_axis_violation(small_mesh):
    from jax.sharding import PartitionSpec as P

    from ddl_tpu.analysis.contracts import _check_boundary

    probe = _probe()
    _check_boundary(
        probe,
        {"in_specs": {"inputs": P("data", "batch_x")}},
        small_mesh,
    )
    assert _rules(probe.findings) == ["contract-axis"]
    assert "batch_x" in probe.findings[0].message
    assert probe.findings[0].path.endswith("train/lm_steps.py")


def test_contract_boundary_violation(small_mesh):
    from jax.sharding import PartitionSpec as P

    from ddl_tpu.analysis.contracts import _check_boundary

    probe = _probe()
    _check_boundary(
        probe, {"in_specs": {"inputs": P(None, "seq")}}, small_mesh
    )
    assert _rules(probe.findings) == ["contract-boundary"]


def test_contract_replication_violation_and_waiver(small_mesh):
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from ddl_tpu.analysis.contracts import _check_params

    replicated = jax.device_put(
        jnp.zeros((128, 128)), NamedSharding(small_mesh, P())
    )
    sharded = jax.device_put(
        jnp.zeros((128, 128)), NamedSharding(small_mesh, P("model", None))
    )
    params = {"big_replicated": replicated, "big_sharded": sharded}
    probe = _probe()
    _check_params(
        probe, params, small_mesh,
        {"replicated_params_ok": False},
    )
    assert _rules(probe.findings) == ["contract-replicated"]
    assert "big_replicated" in probe.findings[0].message

    # the waiver is an explicit P() rule in the factory's rule table now
    # (the replicated_ok_leaves hand list is retired)
    from ddl_tpu.parallel.rules import RuleTable

    table = RuleTable(
        family="test",
        rules=(("big_replicated", P()), ("big_sharded", P("model", None))),
        in_specs={},
    )
    waived = _probe()
    _check_params(
        waived, params, small_mesh,
        {"replicated_params_ok": False, "rule_table": table},
    )
    assert waived.findings == []
    assert any("explicit in the rule table" in n for n in waived.notes)


def test_contract_trace_violation():
    from ddl_tpu.analysis.contracts import _lower

    class Boom:
        def lower(self, *a):
            raise ValueError("rank mismatch: everything is broken")

    probe = _probe()
    _lower(probe, Boom(), 1, 2, what="synthetic step")
    assert _rules(probe.findings) == ["contract-trace"]
    assert "rank mismatch" in probe.findings[0].message


def test_contract_probes_run_clean():
    """The shipped factories satisfy their own contracts end to end
    (slow-ish: builds all six probe step families — the four flat/decode
    ones plus the LM and ViT pipeline compositions — on the CPU
    mesh)."""
    from ddl_tpu.analysis.contracts import PROBES, run_contracts

    assert {name for name, _ in PROBES} >= {
        "lm_pipeline", "vit_pipeline",
    }, "pipeline factories must be probed too"
    report = run_contracts()
    assert report.findings == [], "\n".join(
        f.format() for f in report.findings
    )
    # the ViT embed waiver must be visible, not silent
    assert any("patch_embed" in n for n in report.notes)


def test_lm_factory_declares_contract():
    import jax
    import optax

    from ddl_tpu.parallel.sharding import LMMeshSpec
    from ddl_tpu.train.lm_steps import TOKEN_SPEC, make_lm_step_fns

    from ddl_tpu.models.transformer import LMConfig

    fns = make_lm_step_fns(
        LMConfig(vocab_size=64, d_model=16, n_layers=1, n_heads=2,
                 head_dim=8, d_ff=32, compute_dtype="float32"),
        LMMeshSpec(), optax.sgd(0.1), jax.random.key(0), batch=2, seq_len=8,
    )
    c = fns.train.contract
    assert c["in_specs"]["inputs"] == TOKEN_SPEC
    assert c["donate_state"] is True


# ---------------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------------


def test_lint_cli_clean_package_and_json(capsys):
    from ddl_tpu.analysis.cli import main

    rc = main([
        "--no-contracts", "--baseline", str(REPO / "LINT_BASELINE.json"),
    ])
    out = capsys.readouterr().out
    assert rc == 0 and "lint: clean" in out

    rc = main([
        "--json", "--no-contracts",
        "--baseline", str(REPO / "LINT_BASELINE.json"),
    ])
    payload = json.loads(capsys.readouterr().out)
    assert rc == 0 and payload["ok"] and payload["new"] == []


def test_lint_cli_fails_on_violations_with_file_line(tmp_path, capsys):
    from ddl_tpu.analysis.cli import main

    bad = tmp_path / "bad.py"
    shutil.copy(FIXTURES / "bad_traced.py", bad)
    rc = main([str(bad)])
    out = capsys.readouterr().out
    assert rc == 1
    assert "bad.py:20" in out or "bad.py:" in out  # file:line findings
    assert "[host-sync]" in out


def test_lint_cli_update_baseline(tmp_path, capsys):
    from ddl_tpu.analysis.cli import main

    bad = tmp_path / "bad.py"
    shutil.copy(FIXTURES / "bad_misc.py", bad)
    baseline = tmp_path / "base.json"
    # seed the baseline from the current findings...
    rc = main([str(bad), "--baseline", str(baseline), "--update-baseline"])
    assert rc == 0 and baseline.exists()
    capsys.readouterr()
    # ...after which the same findings are known, not new
    rc = main([str(bad), "--baseline", str(baseline)])
    out = capsys.readouterr().out
    assert rc == 0 and "baselined finding(s)" in out


# ---------------------------------------------------------------------------
# runtime registry guard
# ---------------------------------------------------------------------------


def test_event_writer_warns_on_unregistered_kind(tmp_path):
    from ddl_tpu.obs import EventWriter

    w = EventWriter(tmp_path, "job", host=0)
    with pytest.warns(UserWarning, match="not registered"):
        w.emit("definitely_not_registered_kind")
    w.close()
