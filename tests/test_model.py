"""DenseNet structure tests: parity with torchvision densenet121 shapes."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ddl_tpu.config import ModelConfig
from ddl_tpu.models import (
    build_stages,
    count_params,
    forward_stages,
    init_stages,
    stage_boundary_shapes,
)

# torchvision densenet121 with a 5-class head (reference single.py:297-299):
# features 6,953,856 params + classifier 1024*5+5.
DENSENET121_5CLASS_PARAMS = 6_958_981


@pytest.fixture(scope="module")
def full_cfg():
    return ModelConfig()


def _abstract_param_counts(cfg, num_stages=None, image_size=224):
    """Per-stage param counts via eval_shape (no FLOPs, fast on CPU)."""
    stages = build_stages(cfg, num_stages=num_stages)
    counts = []
    x = jax.ShapeDtypeStruct((1, image_size, image_size, 3), jnp.float32)
    for stage in stages:
        variables = jax.eval_shape(
            lambda k, v, s=stage: s.init(k, v, train=False), jax.random.key(0), x
        )
        counts.append(count_params(variables["params"]))
        x = jax.eval_shape(lambda v, y, s=stage: s.apply(v, y, train=False), variables, x)
    return counts


def test_param_count_matches_torchvision(full_cfg):
    assert sum(_abstract_param_counts(full_cfg, num_stages=1)) == DENSENET121_5CLASS_PARAMS


def test_staged_split_param_counts(full_cfg):
    """The 2-stage split must partition the exact same parameters."""
    s0, s1 = _abstract_param_counts(full_cfg)
    assert s0 + s1 == DENSENET121_5CLASS_PARAMS
    # the reference split is unbalanced toward the later blocks (debug.py
    # prints per-stage counts); sanity-check the imbalance direction.
    assert 0 < s0 < s1


def test_boundary_shape(full_cfg):
    # split at denseblock3 start: activation entering block3 is 14x14x256 for
    # 224x224 inputs (stem /4 -> 56, transition1 -> 28, transition2 -> 14).
    assert stage_boundary_shapes(full_cfg, 224) == [(14, 14, 256)]


def test_forward_shapes_and_dtype(tiny_model_cfg):
    stages = build_stages(tiny_model_cfg)
    params, batch_stats = init_stages(stages, jax.random.key(0), image_size=16)
    x = jnp.ones((2, 16, 16, 3), jnp.float32)
    logits, new_stats = forward_stages(stages, params, batch_stats, x, train=True)
    assert logits.shape == (2, 5)
    assert logits.dtype == jnp.float32
    # batch_stats must actually update in train mode
    old = jax.tree_util.tree_leaves(batch_stats)
    new = jax.tree_util.tree_leaves(new_stats)
    assert any(not np.allclose(a, b) for a, b in zip(old, new))
    # eval mode leaves them untouched
    _, same_stats = forward_stages(stages, params, batch_stats, x, train=False)
    for a, b in zip(jax.tree_util.tree_leaves(batch_stats), jax.tree_util.tree_leaves(same_stats)):
        np.testing.assert_array_equal(a, b)


def test_single_vs_staged_forward_identical(tiny_model_cfg):
    """Splitting into stages must not change the math."""
    stages2 = build_stages(tiny_model_cfg)
    stages1 = build_stages(tiny_model_cfg, num_stages=1)
    p2, s2 = init_stages(stages2, jax.random.key(0), image_size=16)
    x = jax.random.normal(jax.random.key(1), (3, 16, 16, 3))

    # Rebuild the single-stage params from the 2-stage params: the module
    # names are disjoint (blocks keep their global indices), so merging the
    # dicts gives the exact single-stage tree.
    merged_params = {**p2[0], **p2[1]}
    merged_stats = {**s2[0], **s2[1]}
    out2, _ = forward_stages(stages2, p2, s2, x, train=False)
    out1, _ = forward_stages(stages1, (merged_params,), (merged_stats,), x, train=False)
    np.testing.assert_allclose(np.asarray(out1), np.asarray(out2), rtol=1e-6, atol=1e-6)


def test_bad_split_rejected(tiny_model_cfg):
    import dataclasses

    cfg = dataclasses.replace(tiny_model_cfg, split_blocks=(0,))
    with pytest.raises(ValueError):
        build_stages(cfg)


@pytest.mark.parametrize("alt_impl", ["buffer", "packed"])
def test_alt_block_impl_matches_concat(tiny_model_cfg, alt_impl):
    """dense_block_impl='buffer' (preallocated feature buffer, in-place
    strips) and 'packed' (lane-aligned packs, implicit concat via
    per-pack 1x1 contraction, stats-once) are the same math as the
    textbook concat form: identical params, forward, train-mode batch
    stats, and gradients."""
    import dataclasses

    x = jax.random.normal(jax.random.key(2), (2, 16, 16, 3))
    outs = {}
    for impl in ("concat", alt_impl):
        cfg = dataclasses.replace(tiny_model_cfg, dense_block_impl=impl)
        stages = build_stages(cfg, num_stages=1)
        params, bstats = init_stages(stages, jax.random.key(0), image_size=16)

        def loss(params, bstats, x):
            logits, ns = forward_stages(stages, params, bstats, x, train=True)
            return (logits ** 2).sum(), ns

        (val, ns), grads = jax.value_and_grad(loss, has_aux=True)(
            params, bstats, x
        )
        outs[impl] = (val, ns, grads, params)
    # same init (param tree is impl-independent)
    ca, cb = jax.tree.structure(outs["concat"][3]), jax.tree.structure(outs[alt_impl][3])
    assert ca == cb
    for a, b in zip(jax.tree.leaves(outs["concat"][3]), jax.tree.leaves(outs[alt_impl][3])):
        np.testing.assert_array_equal(a, b)
    np.testing.assert_allclose(outs["concat"][0], outs[alt_impl][0], rtol=1e-5)
    for a, b in zip(jax.tree.leaves(outs["concat"][1]), jax.tree.leaves(outs[alt_impl][1])):
        np.testing.assert_allclose(a, b, atol=1e-5)
    for a, b in zip(jax.tree.leaves(outs["concat"][2]), jax.tree.leaves(outs[alt_impl][2])):
        np.testing.assert_allclose(a, b, atol=1e-4, rtol=1e-4)


def test_packed_bf16_close_to_concat(tiny_model_cfg, monkeypatch):
    """In bf16 compute the packed block accumulates cross-pack partial
    sums in bf16 (deliberate: a bf16 partial write is half the HBM
    traffic; each pack's own contraction still accumulates f32 in the
    MXU), diverging from the concat form's single f32-accumulated
    matmul.  Pin the drift: multi-pack bf16 forward within bf16-level
    tolerance of the concat form."""
    import dataclasses

    from ddl_tpu.models import densenet as dn

    monkeypatch.setattr(dn, "_PACK", 8)  # force several packs
    x = jax.random.normal(jax.random.key(2), (2, 16, 16, 3))
    outs = {}
    for impl in ("concat", "packed"):
        cfg = dataclasses.replace(
            tiny_model_cfg, dense_block_impl=impl, compute_dtype="bfloat16"
        )
        stages = build_stages(cfg, num_stages=1)
        params, bstats = init_stages(stages, jax.random.key(0), image_size=16)
        logits, _ = forward_stages(stages, params, bstats, x, train=True)
        outs[impl] = np.asarray(logits, np.float32)
    # bf16 has ~3 decimal digits; cross-pack reassociation costs at most
    # a few ulps on top
    np.testing.assert_allclose(
        outs["concat"], outs["packed"], atol=0.05, rtol=0.02
    )


def test_packed_multi_pack_and_eval(tiny_model_cfg, monkeypatch):
    """The packed impl with features spanning MULTIPLE lane packs (pack
    width patched to 8 so the tiny config splits/merges/slices across
    packs), in both train and eval mode (eval reads each consumer's own
    running stats, sliced per pack)."""
    import dataclasses

    from ddl_tpu.models import densenet

    monkeypatch.setattr(densenet, "_PACK", 8)
    x = jax.random.normal(jax.random.key(3), (2, 16, 16, 3))
    outs = {}
    for impl in ("concat", "packed"):
        cfg = dataclasses.replace(tiny_model_cfg, dense_block_impl=impl)
        stages = build_stages(cfg, num_stages=1)
        params, bstats = init_stages(stages, jax.random.key(0), image_size=16)
        # one train step to make running stats non-trivial before eval
        logits_tr, ns = forward_stages(stages, params, bstats, x, train=True)
        logits_ev, _ = forward_stages(stages, params, ns, x, train=False)
        outs[impl] = (logits_tr, ns, logits_ev)
    np.testing.assert_allclose(
        np.asarray(outs["concat"][0]), np.asarray(outs["packed"][0]),
        atol=1e-5,
    )
    for a, b in zip(
        jax.tree.leaves(outs["concat"][1]), jax.tree.leaves(outs["packed"][1])
    ):
        np.testing.assert_allclose(a, b, atol=1e-5)
    np.testing.assert_allclose(
        np.asarray(outs["concat"][2]), np.asarray(outs["packed"][2]),
        atol=1e-5,
    )


def test_packed_staged_matches_single(tiny_model_cfg):
    """The packed impl through the PIPELINE staging path (stage boundary
    falls between blocks, where the packed transition hands a dense
    tensor across) equals its single-stage forward."""
    import dataclasses

    cfg = dataclasses.replace(tiny_model_cfg, dense_block_impl="packed")
    stages2 = build_stages(cfg)
    stages1 = build_stages(cfg, num_stages=1)
    p2, s2 = init_stages(stages2, jax.random.key(0), image_size=16)
    x = jax.random.normal(jax.random.key(1), (3, 16, 16, 3))
    merged_params = {**p2[0], **p2[1]}
    merged_stats = {**s2[0], **s2[1]}
    out2, _ = forward_stages(stages2, p2, s2, x, train=True)
    out1, _ = forward_stages(
        stages1, (merged_params,), (merged_stats,), x, train=True
    )
    np.testing.assert_allclose(
        np.asarray(out1), np.asarray(out2), rtol=1e-6, atol=1e-6
    )
