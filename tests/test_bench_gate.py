"""`ddl_tpu bench` — the MFU/steps-per-sec regression gate and the
op-digest renderer (bench/gate.py)."""

import json

import jax
import jax.numpy as jnp
import pytest

from ddl_tpu.bench.gate import main as bench_main


@pytest.fixture()
def baseline(tmp_path):
    p = tmp_path / "BASELINE.json"
    p.write_text(json.dumps({
        "metric": "whatever",
        "headline": {
            "metric": "densenet121_train_steps_per_sec_bs30_1chip",
            "steps_per_sec": 72.589,
            "mfu": 0.1871,
        },
    }))
    return p


def _result(tmp_path, value, mfu):
    p = tmp_path / "result.json"
    p.write_text(json.dumps({
        "metric": "densenet121_train_steps_per_sec_bs30_1chip",
        "value": value, "unit": "steps/sec", "mfu": mfu,
    }) + "\n")
    return p


def test_gate_passes_within_tolerance(tmp_path, baseline, capsys):
    res = _result(tmp_path, 70.0, 0.180)  # ~-3.6% / -3.8%
    rc = bench_main([
        "--result", str(res), "--baseline", str(baseline),
        "--fail-mfu-drop", "0.1", "--fail-slowdown", "0.1",
    ])
    out = capsys.readouterr().out
    assert rc == 0 and "OK" in out


def test_gate_fails_on_mfu_drop(tmp_path, baseline, capsys):
    res = _result(tmp_path, 71.0, 0.12)  # MFU -36%
    rc = bench_main([
        "--result", str(res), "--baseline", str(baseline),
        "--fail-mfu-drop", "0.1",
    ])
    out = capsys.readouterr().out
    assert rc == 1 and "mfu dropped" in out


def test_gate_fails_on_slowdown(tmp_path, baseline, capsys):
    res = _result(tmp_path, 40.0, 0.187)  # steps/s -45%
    rc = bench_main([
        "--result", str(res), "--baseline", str(baseline),
        "--fail-slowdown", "0.5", "--fail-mfu-drop", "0.1",
    ])
    assert rc == 0  # -45% within the 50% gate
    rc = bench_main([
        "--result", str(res), "--baseline", str(baseline),
        "--fail-slowdown", "0.1",
    ])
    assert rc == 1


def test_gate_update_baseline_round_trip(tmp_path, baseline, capsys):
    res = _result(tmp_path, 81.5, 0.21)
    rc = bench_main([
        "--result", str(res), "--baseline", str(baseline),
        "--update-baseline",
    ])
    assert rc == 0
    stored = json.loads(baseline.read_text())["headline"]
    assert stored["steps_per_sec"] == 81.5 and stored["mfu"] == 0.21
    # the new headline becomes the reference: the old number now fails
    old = _result(tmp_path, 72.589, 0.1871)
    rc = bench_main([
        "--result", str(old), "--baseline", str(baseline),
        "--fail-slowdown", "0.05",
    ])
    assert rc == 1


def test_gate_missing_headline_is_usage_error(tmp_path, capsys):
    b = tmp_path / "b.json"
    b.write_text(json.dumps({"metric": "m"}))
    res = _result(tmp_path, 70.0, 0.18)
    rc = bench_main([
        "--result", str(res), "--baseline", str(b),
        "--fail-mfu-drop", "0.1",
    ])
    assert rc == 2


def test_digest_renders_cpu_trace(tmp_path, capsys):
    """`bench digest <dir>` over a real (CPU host-plane) capture: the
    wire-format reader + host fallback produce a non-empty category
    table — the same path the PERF.md digest protocol uses."""
    trace = tmp_path / "trace"

    @jax.jit
    def f(a, b):
        return jnp.tanh(a @ b).sum()

    a = jnp.ones((128, 128))
    f(a, a).block_until_ready()  # compile outside the window
    jax.profiler.start_trace(str(trace))
    for _ in range(3):
        f(a, a).block_until_ready()
    jax.profiler.stop_trace()

    rc = bench_main(["digest", str(trace), "--top", "5"])
    out = capsys.readouterr().out
    assert rc == 0
    assert "total sync-op time" in out and "ms" in out

    out_hbm = out  # text mode printed the optimizer-HBM section too
    assert "optimizer-state HBM per device" in out_hbm
    # ...and the compiled-collective table from HLO_BASELINE.json
    assert "compiled-program collectives" in out

    rc = bench_main(["digest", str(trace), "--json", "--opt-hbm-dp", "4"])
    row = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rc == 0 and row["total_ms"] > 0 and row["ops"]
    fams = {r["family"]: r for r in row["opt_hbm"]}
    assert any(f.startswith("cnn") for f in fams)
    for r in row["opt_hbm"]:
        assert r["dp"] == 4
        assert 0 < r["zero_bytes"] < r["replicated_bytes"]
    hlo = {r["program"]: r for r in row["hlo_collectives"]}
    assert "cnn_dp_zero" in hlo and "serve_decode" in hlo
    assert hlo["cnn_dp_zero"]["count"] > 0
    assert any(k.startswith("all-gather@") and "data" in k
               for k in hlo["cnn_dp_zero"]["collectives"])

    # 0 disables the section (fast path for trace-only digests)
    rc = bench_main(["digest", str(trace), "--opt-hbm-dp", "0"])
    assert rc == 0
    assert "optimizer-state HBM" not in capsys.readouterr().out


def test_opt_hbm_rows_estimates_scale_with_dp():
    from ddl_tpu.bench.gate import opt_hbm_rows

    rows4 = {r["family"]: r for r in opt_hbm_rows(dp=4)}
    rows8 = {r["family"]: r for r in opt_hbm_rows(dp=8)}
    for fam, r4 in rows4.items():
        r8 = rows8[fam]
        # replicated estimate is dp-independent; zero shrinks with dp
        assert r4["replicated_bytes"] == r8["replicated_bytes"]
        assert r8["zero_bytes"] < r4["zero_bytes"] < r4["replicated_bytes"]
        # the saving on eligible leaves is ~(dp-1)/dp: at dp=8 the
        # whole-model saving must exceed the dp=4 bound of 3/4 only on
        # the eligible fraction — just assert monotone + sane here
        assert r4["zero_sharded_leaves"] > 0


def test_digest_missing_trace_is_usage_error(tmp_path, capsys):
    rc = bench_main(["digest", str(tmp_path / "nope")])
    assert rc == 2


def test_cli_routes_bench_subcommand(tmp_path, baseline, capsys):
    from ddl_tpu.cli import main as cli_main

    res = _result(tmp_path, 70.0, 0.18)
    with pytest.raises(SystemExit) as e:
        cli_main([
            "bench", "--result", str(res), "--baseline", str(baseline),
            "--fail-mfu-drop", "0.1",
        ])
    assert e.value.code == 0


def test_gate_fails_closed_on_missing_metric(tmp_path, baseline, capsys):
    """A requested gate whose metric is missing (e.g. a result with no
    'mfu' field because the chip peak was unknown) must FAIL, not
    silently pass — fail-open here is exactly the silent regression the
    gate exists to prevent."""
    p = tmp_path / "result.json"
    p.write_text(json.dumps({
        "metric": "densenet121_train_steps_per_sec_bs30_1chip",
        "value": 70.0, "unit": "steps/sec",  # no mfu
    }) + "\n")
    rc = bench_main([
        "--result", str(p), "--baseline", str(baseline),
        "--fail-mfu-drop", "0.1",
    ])
    out = capsys.readouterr().out
    assert rc == 1 and "cannot gate mfu" in out
    # without the mfu gate the same result passes on steps/sec alone
    rc = bench_main([
        "--result", str(p), "--baseline", str(baseline),
        "--fail-slowdown", "0.1",
    ])
    assert rc == 0
