"""Causal distributed tracing (obs/trace.py), the fleet rollup
(obs/fleet.py), the Prometheus histogram export, and watch push mode.

The load-bearing properties: trace output is VALID Chrome trace-event
JSON (monotonic ts, X/i/M/s/f phases only, every flow's s/f pair
matched by bind id), cross-host ordering is clock-offset corrected,
``--slowest-request`` selection is a pure function of the fold state,
and the fold stays byte-identical warm vs cold with trace kinds in the
stream.
"""

import json

import pytest

# ---------------------------------------------------------------------------
# synthetic streams
# ---------------------------------------------------------------------------


def _ev(host, kind, ts, **kw):
    e = {
        "ts": ts, "mono": ts, "run": f"r{host}", "host": host,
        "step": kw.pop("step", None), "kind": kind,
    }
    e.update(kw)
    return e


def _request_events(host, rid, t, dur, *, dispatches=2, warm=True):
    """The native trace events one served request emits (the same
    shapes serve/engine.py writes), plus its admit/retire/decode."""
    evs = [
        _ev(host, "serve_admit", t + 0.1, request_id=rid, lane=0,
            bucket=8, prompt_len=5, max_new=8, blocks=2,
            queue_delay=0.1, compiled=False),
        _ev(host, "trace_span", t + 0.1, trace=rid,
            span=f"{rid}/queue", parent=f"{rid}/req", name="queue",
            cat="serve", t0=t, t1=t + 0.1, request_id=rid),
        _ev(host, "trace_span", t + 0.2, trace=rid,
            span=f"{rid}/prefill", parent=f"{rid}/req", name="prefill",
            cat="serve", t0=t + 0.1, t1=t + 0.2, request_id=rid,
            lane=0, bucket=8, compiled=False),
    ]
    step = (dur - 0.2) / max(1, dispatches)
    for d in range(dispatches):
        t0 = t + 0.2 + d * step
        evs.append(_ev(
            host, "trace_span", t0 + step, trace=rid,
            span=f"{rid}/d{d}", parent=f"{rid}/req", name="decode",
            cat="serve", t0=t0, t1=t0 + step, request_id=rid, lane=0,
            dispatch=d, steps=4, riders=1,
        ))
    evs += [
        _ev(host, "trace_span", t + dur, trace=rid, span=f"{rid}/req",
            parent=None, name="request", cat="serve", t0=t, t1=t + dur,
            request_id=rid, lane=0, prompt_len=5, new_tokens=8,
            dispatches=dispatches, outcome="ok"),
        _ev(host, "serve_retire", t + dur, request_id=rid, lane=0,
            new_tokens=8, dur=dur, freed_blocks=2),
        _ev(host, "decode", t + dur, request_id=rid, prompt_len=5,
            new_tokens=8, batch=1, dur=dur, queue_delay=0.1, ttft=0.2,
            tok_per_s=8 / dur, warm=warm, chips=1, engine="serve"),
    ]
    return evs


def _write(log_dir, job, host, events, mode="a"):
    d = log_dir / "by_job_id" / job
    d.mkdir(parents=True, exist_ok=True)
    with open(d / f"events-h{host:03d}.jsonl", mode) as f:
        for e in events:
            f.write(json.dumps(e) + "\n")
    return d


def _serve_job(log_dir, job="serve"):
    evs = [_ev(0, "run_start", 1.0, family="serve")]
    evs += _request_events(0, "c0", 10.0, 0.5)
    evs += _request_events(0, "c1", 11.0, 1.4, dispatches=3)
    evs.append(_ev(
        0, "trace_mark", 12.0, trace="c2", span="c2/shed", name="shed",
        cat="serve", request_id="c2", reason="queue_full",
        policy="reject",
    ))
    evs.append(_ev(0, "run_end", 20.0, phases={}))
    _write(log_dir, job, 0, evs)
    return job


# 3-host pod with skewed clocks: host h's wall clock shows true + OFF[h]
_OFF = {0: 0.0, 1: 5.0, 2: -3.0}


def _pod_job(log_dir, job="pod"):
    for h in range(3):
        def w(true_ts, h=h):
            return true_ts + _OFF[h]

        evs = [_ev(h, "run_start", w(1.0), family="lm")]
        for name, bt in (("start", 5.0), ("warm", 8.0)):
            evs.append(_ev(
                h, "coord_barrier", w(bt + 0.001 * h), name=name,
                wait=0.2, completed_ts=w(bt), arrive_ts=w(bt - 0.2),
            ))
        for p in range(3):
            evs.append(_ev(
                h, "period", w(10.0 + p), step=p, period=p, steps=10,
                elapsed=1.0, steps_per_sec=10.0, phases={"step": 0.8},
                compiles=0,
                rates={"mfu": 0.21, "tokens_per_sec": 100.0},
            ))
        if h == 1:
            evs.append(_ev(
                h, "stall", w(100.0), step=30, age=5.0, deadline=4.0,
                stacks={"t": "tb"},
            ))
        evs.append(_ev(
            h, "pod_restart", w(102.2 + 0.01 * h), epoch=1,
            reason="peer_stale", proposer=1, crashes=0, preemptions=1,
            delay=0.0, decision_ts=w(102.0),
        ))
        evs.append(_ev(
            h, "coord_barrier", w(103.0 + 0.002 * h), name="e1-join",
            wait=0.5, completed_ts=w(103.0),
            arrive_ts=w(102.5 + 0.1 * h),
        ))
        evs.append(_ev(
            h, "restart_latency", w(106.0), step=31, latency=4.0,
            decision_ts=w(102.0), repoch=1,
        ))
        _write(log_dir, job, h, evs)
    return job


# ---------------------------------------------------------------------------
# Chrome trace-format validity (the golden contract)
# ---------------------------------------------------------------------------


def _assert_valid_chrome_trace(trace):
    evs = trace["traceEvents"]
    assert evs, "empty trace"
    assert all(e["ph"] in ("X", "i", "M", "s", "f") for e in evs)
    ts = [e["ts"] for e in evs]
    assert ts == sorted(ts), "trace events not ts-monotonic"
    for e in evs:
        assert e["ts"] >= 0
        if e["ph"] == "X":
            assert e["dur"] >= 1
            assert isinstance(e["pid"], int) and isinstance(e["tid"], int)
        if e["ph"] == "i":
            assert e["s"] == "t"
    starts = sorted(e["id"] for e in evs if e["ph"] == "s")
    finishes = sorted(e["id"] for e in evs if e["ph"] == "f")
    assert starts == finishes, "unmatched flow bind ids"
    assert len(set(starts)) == len(starts)
    # every flow arrow points forward in time (Perfetto drops or
    # mangles backward s->f pairs)
    pairs = {}
    for e in evs:
        if e["ph"] in ("s", "f"):
            pairs.setdefault(e["id"], {})[e["ph"]] = e["ts"]
    for pid, pair in pairs.items():
        assert pair["s"] <= pair["f"], f"backward flow id {pid}"
    # round-trips through JSON (what --out writes)
    json.loads(json.dumps(trace))


def test_request_trace_golden(tmp_path):
    from ddl_tpu.obs.trace import trace_job

    job = _serve_job(tmp_path)
    trace = trace_job(tmp_path, job, request="c1")
    _assert_valid_chrome_trace(trace)
    names = [e["name"] for e in trace["traceEvents"] if e["ph"] == "X"]
    # the acceptance shape: queue, prefill, EVERY ridden dispatch, root
    assert names.count("decode") == 3
    for required in ("request", "queue", "prefill"):
        assert required in names
    marks = [e["name"] for e in trace["traceEvents"] if e["ph"] == "i"]
    assert "admit" in marks and "retire" in marks
    # causally linked: queue -> prefill -> d0 -> d1 -> d2 -> retire
    assert sum(1 for e in trace["traceEvents"] if e["ph"] == "s") == 5
    # the root span covers the whole request
    root = next(
        e for e in trace["traceEvents"]
        if e["ph"] == "X" and e["name"] == "request"
    )
    assert root["dur"] == pytest.approx(1.4e6, rel=0.01)


def test_shed_request_trace_is_terminal_mark(tmp_path):
    from ddl_tpu.obs.trace import trace_job

    job = _serve_job(tmp_path)
    trace = trace_job(tmp_path, job, request="c2")
    _assert_valid_chrome_trace(trace)
    marks = [e["name"] for e in trace["traceEvents"] if e["ph"] == "i"]
    assert marks == ["shed"]


def test_step_trace_spans_phases(tmp_path):
    from ddl_tpu.obs.trace import trace_job

    for h in range(2):
        _write(tmp_path, "steps", h, [
            _ev(h, "span", 10.0 + 0.1 * h, step=7, name="step",
                dur=0.08, depth=0, period=0),
            _ev(h, "span", 10.2 + 0.1 * h, step=7, name="data_wait",
                dur=0.01, depth=0, period=0),
            _ev(h, "span", 11.0, step=8, name="step", dur=0.08,
                depth=0, period=0),
        ])
    trace = trace_job(tmp_path, "steps", step=7)
    _assert_valid_chrome_trace(trace)
    xs = [e for e in trace["traceEvents"] if e["ph"] == "X"]
    assert len(xs) == 4  # both hosts' step+data_wait for step 7 only
    assert {e["name"] for e in xs} == {"step", "data_wait"}


def test_step_trace_renders_schedule_lanes(tmp_path):
    """With a pipe_schedule event on record, `obs trace --step` adds
    the modeled per-stage F/B/W lanes beside the measured phase spans —
    one Perfetto thread per stage, every unit marked modeled, scaled
    into the step's measured window."""
    from ddl_tpu.obs.trace import trace_job

    _write(tmp_path, "zbsteps", 0, [
        _ev(0, "pipe_schedule", 5.0, schedule="zb", pipe=2,
            microbatches=4, virtual=1),
        _ev(0, "span", 10.0, step=3, name="step", dur=0.08, depth=0,
            period=0),
        _ev(0, "span", 10.2, step=3, name="fence", dur=0.01, depth=0,
            period=0),
    ])
    trace = trace_job(tmp_path, "zbsteps", step=3)
    _assert_valid_chrome_trace(trace)
    xs = [e for e in trace["traceEvents"] if e["ph"] == "X"]
    lanes = [e for e in xs if e.get("args", {}).get("modeled")]
    phases = {e["args"]["phase"] for e in lanes}
    assert phases == {"F", "B", "W"}
    # every stage contributes M units of each phase
    per_stage = {}
    for e in lanes:
        per_stage.setdefault(e["tid"], []).append(e)
    assert set(per_stage) == {0, 1}
    for units in per_stage.values():
        assert len(units) == 3 * 4
    # stage threads are named and the measured spans are still there
    names = {e["args"]["name"] for e in trace["traceEvents"]
             if e["ph"] == "M" and e["name"] == "thread_name"}
    assert {"stage 0", "stage 1"} <= names
    assert {"step", "fence"} <= {e["name"] for e in xs}

    # a malformed/unmodeled pipe_schedule event degrades to no lanes,
    # never a crash
    _write(tmp_path, "badsched", 0, [
        _ev(0, "pipe_schedule", 5.0, schedule="1f1b", pipe=2,
            microbatches=4, virtual=2),
        _ev(0, "span", 10.0, step=1, name="step", dur=0.05, depth=0,
            period=0),
    ])
    t2 = trace_job(tmp_path, "badsched", step=1)
    assert not [e for e in t2["traceEvents"]
                if e["ph"] == "X" and e.get("args", {}).get("modeled")]


def test_selector_errors_are_actionable(tmp_path):
    from ddl_tpu.obs.trace import trace_job

    job = _serve_job(tmp_path)
    with pytest.raises(SystemExit, match="no trace events for request"):
        trace_job(tmp_path, job, request="nope")
    with pytest.raises(SystemExit, match="out of range"):
        trace_job(tmp_path, job, incident=99)
    with pytest.raises(SystemExit, match="exactly one"):
        trace_job(tmp_path, job, request="c1", step=3)
    with pytest.raises(SystemExit, match="exactly one"):
        trace_job(tmp_path, job)


# ---------------------------------------------------------------------------
# slowest-request selection (fold-side)
# ---------------------------------------------------------------------------


def test_slowest_request_selection(tmp_path):
    from ddl_tpu.obs.fold import fold_job
    from ddl_tpu.obs.trace import trace_job

    job = _serve_job(tmp_path)
    fold = fold_job(tmp_path, job)
    cell = fold.trace_totals()["slowest"]
    assert cell is not None and cell[1] == "c1"
    assert cell[0] == pytest.approx(1.4)
    trace = trace_job(tmp_path, job, slowest=True)
    assert trace["otherData"]["trace"] == "request c1"

    # the summary surfaces the same selection
    from ddl_tpu.obs.report import summarize_from_fold

    s = summarize_from_fold(fold)
    assert s["trace"]["requests"] == 2
    assert s["trace"]["slowest"]["request"] == "c1"


def test_slowest_request_empty_job_errors(tmp_path):
    from ddl_tpu.obs.trace import trace_job

    _write(tmp_path, "plain", 0, [_ev(0, "run_start", 1.0)])
    with pytest.raises(SystemExit, match="no request trace spans"):
        trace_job(tmp_path, "plain", slowest=True)


# ---------------------------------------------------------------------------
# warm == cold with trace kinds present
# ---------------------------------------------------------------------------


def test_fold_byte_identity_with_trace_kinds(tmp_path):
    from ddl_tpu.obs.fold import fold_job
    from ddl_tpu.obs.pod import pod_summary_from_fold, render_pod_summary
    from ddl_tpu.obs.report import render_summary, summarize_from_fold

    job = _serve_job(tmp_path)

    def render(cache):
        fold = fold_job(tmp_path, job, cache=cache)
        return (
            render_summary(summarize_from_fold(fold), job)
            + "\n"
            + render_pod_summary(pod_summary_from_fold(fold), job)
        )

    warm1 = render(cache=True)  # builds the sidecar
    # append MORE trace events, resume the fold, compare to cold
    _write(
        tmp_path, job, 0,
        _request_events(0, "c9", 30.0, 2.0, dispatches=1),
    )
    warm2 = render(cache=True)
    cold2 = render(cache=False)
    assert warm2 == cold2
    assert warm1 != warm2  # the appended request is visible
    # the new request is now the slowest, through the resumed fold too
    fold = fold_job(tmp_path, job, cache=True)
    assert fold.trace_totals()["slowest"][1] == "c9"


# ---------------------------------------------------------------------------
# clock-offset-corrected cross-host ordering (3 synthetic hosts)
# ---------------------------------------------------------------------------


def test_incident_trace_cross_host_ordering(tmp_path):
    from ddl_tpu.obs.fold import estimate_clock_offsets, fold_job
    from ddl_tpu.obs.trace import trace_job

    job = _pod_job(tmp_path)
    fold = fold_job(tmp_path, job)
    offsets = estimate_clock_offsets({
        sf.host: sf.barrier_ts for sf in fold.streams.values()
    })
    # the fit recovers the injected skew (up to the common mean shift)
    rel = {h: offsets[h] - offsets[0] for h in offsets}
    assert rel[1] == pytest.approx(_OFF[1] - _OFF[0], abs=0.05)
    assert rel[2] == pytest.approx(_OFF[2] - _OFF[0], abs=0.05)

    trace = trace_job(tmp_path, job, incident=0)
    _assert_valid_chrome_trace(trace)
    evs = trace["traceEvents"]
    stall = next(e for e in evs if e["ph"] == "X" and e["name"] == "stall")
    decisions = [
        e for e in evs
        if e["ph"] == "i" and e["name"].startswith("pod_restart")
    ]
    bars = [
        e for e in evs
        if e["ph"] == "X" and e["name"] == "barrier:e1-join"
    ]
    relaunches = [
        e for e in evs
        if e["ph"] == "X" and e["name"] == "relaunch->first-step"
    ]
    # the pod-wide decision renders ONCE, from the proposer's event
    # (its decision_ts is in the proposer's clock domain, so only the
    # proposer's fitted offset corrects it truly)
    assert len(decisions) == 1 and len(bars) == 3 and len(relaunches) == 3
    # true order after correction: stall start < decision < barrier
    # completion; the raw clocks disagree by up to 8 seconds, so any
    # uncorrected merge would scramble this
    for d in decisions:
        assert stall["ts"] < d["ts"]
        for b in bars:
            assert d["ts"] <= b["ts"] + b["dur"]
    # all hosts observed the join complete at (nearly) one instant
    ends = sorted(b["ts"] + b["dur"] for b in bars)
    assert ends[-1] - ends[0] < 20_000  # < 20ms in us after correction
    # relaunch spans originate at the pod-wide decision instant
    for r in relaunches:
        assert abs(r["ts"] - decisions[0]["ts"]) < 250_000
    # flow arrows: decision -> each barrier, each barrier -> first step
    assert sum(1 for e in evs if e["ph"] == "s") >= 6


def test_incident_clustering_gap(tmp_path):
    from ddl_tpu.obs.trace import collect_incidents

    streams = {0: [
        _ev(0, "anomaly", 100.0, type="loss_spike", value=9.0),
        _ev(0, "profile_capture", 101.0, ok=True, trigger="loss_spike",
            trace_dir="/tmp/x"),
        _ev(0, "anomaly", 500.0, type="loss_spike", value=8.0),
    ]}
    incidents = collect_incidents(streams)
    assert len(incidents) == 2
    assert len(incidents[0]["events"]) == 2
    assert incidents[1]["t0"] == 500.0


def test_slow_restart_stays_one_incident(tmp_path):
    """A relaunch whose first step takes longer than the cluster gap
    (40s recompile) must still land in the restart's incident: the
    restart_latency event clusters on its DECISION instant."""
    from ddl_tpu.obs.trace import trace_job

    _write(tmp_path, "slow", 0, [
        _ev(0, "run_start", 1.0),
        _ev(0, "pod_restart", 100.2, epoch=1, reason="crash",
            proposer=0, crashes=1, preemptions=0, delay=0.0,
            decision_ts=100.0),
        _ev(0, "coord_barrier", 101.0, name="e1-join", wait=0.5,
            completed_ts=101.0, arrive_ts=100.5),
        # first step completes 45s after the decision — past the 30s
        # gap from the emission-ts perspective
        _ev(0, "restart_latency", 145.0, step=31, latency=45.0,
            decision_ts=100.0, repoch=1),
    ])
    trace = trace_job(tmp_path, "slow", incident=0)
    _assert_valid_chrome_trace(trace)
    names = [e["name"] for e in trace["traceEvents"] if e["ph"] == "X"]
    assert "relaunch->first-step" in names
    assert "barrier:e1-join" in names
    with pytest.raises(SystemExit, match="out of range"):
        trace_job(tmp_path, "slow", incident=1)  # no spurious second


def test_anomaly_capture_flow(tmp_path):
    from ddl_tpu.obs.trace import trace_job

    _write(tmp_path, "anom", 0, [
        _ev(0, "run_start", 1.0),
        _ev(0, "anomaly", 100.0, step=5, type="loss_spike", value=9.0,
            baseline=1.0),
        _ev(0, "profile_capture", 101.0, step=6, ok=True,
            trigger="loss_spike", trace_dir="/tmp/x",
            digest={"ops": {"dot": 1.0}}),
    ])
    trace = trace_job(tmp_path, "anom", incident=0)
    _assert_valid_chrome_trace(trace)
    assert sum(1 for e in trace["traceEvents"] if e["ph"] == "s") == 1


def test_repeated_anomaly_capture_binds_latest(tmp_path):
    """Two anomalies of the same type in one incident, each arming its
    own capture: every capture's flow must originate at the LATEST
    preceding anomaly, never point backward to a later one."""
    from ddl_tpu.obs.trace import trace_job

    _write(tmp_path, "anom2", 0, [
        _ev(0, "run_start", 1.0),
        _ev(0, "anomaly", 100.0, step=5, type="loss_spike", value=9.0),
        _ev(0, "profile_capture", 101.0, step=6, ok=True,
            trigger="loss_spike", trace_dir="/tmp/x1"),
        _ev(0, "anomaly", 110.0, step=8, type="loss_spike", value=8.0),
        _ev(0, "profile_capture", 111.0, step=9, ok=True,
            trigger="loss_spike", trace_dir="/tmp/x2"),
    ])
    trace = trace_job(tmp_path, "anom2", incident=0)
    _assert_valid_chrome_trace(trace)
    evs = trace["traceEvents"]
    assert sum(1 for e in evs if e["ph"] == "s") == 2
    # each flow's source (s) precedes its sink (f): no backward arrows
    by_id = {}
    for e in evs:
        if e["ph"] in ("s", "f"):
            by_id.setdefault(e["id"], {})[e["ph"]] = e["ts"]
    for pair in by_id.values():
        assert pair["s"] <= pair["f"]


# ---------------------------------------------------------------------------
# fleet rollup over two jobs
# ---------------------------------------------------------------------------


def test_fleet_rollup_two_jobs(tmp_path):
    from ddl_tpu.obs.fleet import (
        fleet_prometheus_text,
        fleet_summary,
        render_fleet,
    )

    _serve_job(tmp_path, "job-serve")
    _pod_job(tmp_path, "job-pod")
    s = fleet_summary(tmp_path)
    assert set(s) == {"job-serve", "job-pod"}

    pod = s["job-pod"]
    assert pod["hosts"] == 3
    assert pod["steps"] == 30  # representative host, not 3x-inflated
    assert pod["steps_per_sec"] == pytest.approx(10.0)
    assert pod["mfu"] == pytest.approx(0.21)
    # ONE pod-wide restart, though all 3 hosts emitted their own
    # pod_restart copy: distinct epochs dedupe, not per-host sums
    assert pod["restarts"] == 1
    assert pod["stalls"] == 1
    assert pod["incidents"] == pod["restarts"] + pod["anomalies"] + 1

    serve = s["job-serve"]
    assert serve["requests"] == 2
    assert serve["ttft_p99_s"] is not None
    assert serve["slowest_request"] == "c1"

    table = render_fleet(s, str(tmp_path), now=200.0)
    assert "job-serve" in table and "job-pod" in table
    assert "p99_ttft" in table and "mfu" in table

    prom = fleet_prometheus_text(tmp_path)
    assert 'job_id="job-serve"' in prom
    assert 'job_id="job-pod"' in prom
    # one header per family even with two jobs filled in
    assert prom.count("# TYPE ddl_obs_steps_total counter") == 1
    assert 'ddl_obs_mfu{host="0",job_id="job-pod",repoch="0"}' in prom


def test_fleet_cli(tmp_path, capsys):
    from ddl_tpu.obs.report import main

    _serve_job(tmp_path, "j1")
    main(["fleet", str(tmp_path), "--json"])
    out = json.loads(capsys.readouterr().out)
    assert out["j1"]["requests"] == 2
    with pytest.raises(SystemExit, match="no jobs"):
        main(["fleet", str(tmp_path / "empty")])
    # --json --prom keeps stdout pure JSON (status goes to stderr)
    main(["fleet", str(tmp_path), "--json", "--prom",
          str(tmp_path / "f.prom")])
    captured = capsys.readouterr()
    json.loads(captured.out)
    assert "wrote" in captured.err
    assert (tmp_path / "f.prom").exists()


# ---------------------------------------------------------------------------
# Prometheus histogram export (t-digest rank)
# ---------------------------------------------------------------------------


def test_tdigest_rank_exact_regime():
    import numpy as np

    from ddl_tpu.obs.serving import TDigest

    dig = TDigest()
    vals = [0.01, 0.02, 0.02, 0.5, 1.5]
    for v in vals:
        dig.add(v)
    assert dig.rank(0.005) == 0.0
    assert dig.rank(0.02) == 3.0
    assert dig.rank(0.4) == 3.0
    assert dig.rank(2.0) == 5.0
    assert TDigest().rank(1.0) is None
    # compressed regime stays monotone and pins the extremes
    big = TDigest(compression=16, exact_max=32)
    rng = np.random.default_rng(0)
    data = sorted(rng.exponential(0.1, 500))
    for v in data:
        big.add(float(v))
    ranks = [big.rank(x) for x in (0.01, 0.05, 0.1, 0.5, 10.0)]
    assert ranks == sorted(ranks)
    assert ranks[-1] == 500.0
    # consistent with numpy's empirical CDF to a few percent
    emp = sum(1 for v in data if v <= 0.1)
    assert ranks[2] == pytest.approx(emp, rel=0.1)


def test_export_histogram_series(tmp_path):
    from ddl_tpu.obs.export import LATENCY_BUCKETS, prometheus_text
    from ddl_tpu.obs.fold import fold_job

    job = _serve_job(tmp_path)
    text = prometheus_text(fold_job(tmp_path, job), job)
    lines = text.splitlines()
    assert "# TYPE ddl_obs_decode_latency_hist_seconds histogram" in lines
    buckets = [
        float(ln.rsplit(" ", 1)[1]) for ln in lines
        if ln.startswith("ddl_obs_decode_latency_hist_seconds_bucket")
    ]
    assert len(buckets) == len(LATENCY_BUCKETS) + 1  # +Inf
    assert buckets == sorted(buckets)  # cumulative
    count = next(
        float(ln.rsplit(" ", 1)[1]) for ln in lines
        if ln.startswith("ddl_obs_decode_latency_hist_seconds_count")
    )
    assert buckets[-1] == count == 2.0  # both warm requests
    # le labels render in bound order, not lexicographic
    le_lines = [
        ln for ln in lines
        if ln.startswith("ddl_obs_decode_latency_hist_seconds_bucket")
    ]
    les = [ln.split('le="')[1].split('"')[0] for ln in le_lines]
    assert les[-1] == "+Inf"
    assert [float(x) for x in les[:-1]] == sorted(
        float(x) for x in les[:-1]
    )
    # the quantile gauges are still there, unchanged family
    assert "# TYPE ddl_obs_decode_latency_seconds gauge" in lines
    # ttft histogram too
    assert "# TYPE ddl_obs_decode_ttft_hist_seconds histogram" in lines


# ---------------------------------------------------------------------------
# watch push mode
# ---------------------------------------------------------------------------


def test_stream_signature_change_detector(tmp_path):
    from ddl_tpu.obs.report import _job_dir
    from ddl_tpu.obs.watch import stream_signature

    job = _serve_job(tmp_path)
    d = _job_dir(tmp_path, job)
    sig1 = stream_signature(d)
    assert sig1 and sig1 == stream_signature(d)  # stable when idle
    _write(tmp_path, job, 0, [_ev(0, "heartbeat", 50.0, step=1)])
    assert stream_signature(d) != sig1  # append detected
    assert stream_signature(tmp_path / "nope") == ()


def test_watch_push_redraws_on_append_before_interval(tmp_path, capsys):
    """With a huge --interval, the push loop still redraws as soon as a
    stream grows: the second frame must arrive from the appender, not
    the interval timer."""
    import threading
    import time as _time

    from ddl_tpu.obs.watch import watch

    job = _serve_job(tmp_path)

    def append_soon():
        _time.sleep(0.3)
        _write(tmp_path, job, 0, [_ev(0, "heartbeat", 50.0, step=1)])

    t = threading.Thread(target=append_soon)
    t.start()
    start = _time.monotonic()
    watch(
        tmp_path, job, interval=30.0, cache=True, max_frames=2,
        poll_s=0.05,
    )
    wall = _time.monotonic() - start
    t.join()
    assert wall < 10.0, f"push mode did not trigger (took {wall:.1f}s)"
    frames = capsys.readouterr().out
    assert frames.count("== obs watch") == 2


def test_watch_interval_is_max_wait(tmp_path, capsys):
    """No appends at all: the loop still redraws once the interval
    elapses (the age column must keep moving on an idle job)."""
    import time as _time

    from ddl_tpu.obs.watch import watch

    job = _serve_job(tmp_path)
    start = _time.monotonic()
    watch(
        tmp_path, job, interval=0.2, cache=True, max_frames=2,
        poll_s=0.05,
    )
    assert _time.monotonic() - start >= 0.2
    assert capsys.readouterr().out.count("== obs watch") == 2


# ---------------------------------------------------------------------------
# the real engine emits a traceable request path (CPU JAX e2e)
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def lm():
    import jax
    import jax.numpy as jnp

    import flax.linen as nn

    from ddl_tpu.models.transformer import LMConfig, TransformerLM
    from ddl_tpu.parallel.sharding import LMMeshSpec

    cfg = LMConfig(
        vocab_size=64, d_model=32, n_layers=2, n_heads=4, head_dim=8,
        d_ff=64, compute_dtype="float32",
    )
    params = nn.meta.unbox(
        TransformerLM(cfg, None).init(
            jax.random.key(0), jnp.zeros((1, 8), jnp.int32)
        )["params"]
    )
    return cfg, params, LMMeshSpec()


@pytest.mark.slow
def test_engine_request_trace_e2e(tmp_path, lm):
    """A real ServeEngine run yields a loadable, causally-complete
    trace for its slowest request — the CPU half of the acceptance
    drive (the CLI half is in the verify skill)."""
    import numpy as np

    from ddl_tpu.obs import EventWriter
    from ddl_tpu.obs.fold import fold_job
    from ddl_tpu.obs.trace import trace_job
    from ddl_tpu.serve.engine import ServeEngine

    cfg, params, spec = lm
    obs = EventWriter(tmp_path, "trace-e2e")
    eng = ServeEngine(
        cfg, params, spec, block_size=8, num_blocks=32, max_batch=2,
        max_steps_per_dispatch=4, obs=obs,
    )
    for i, (plen, mn) in enumerate([(5, 6), (9, 10), (3, 2)]):
        eng.submit(
            np.arange(1, plen + 1, dtype=np.int32), mn,
            request_id=f"q{i}",
        )
    eng.run()
    obs.close()

    fold = fold_job(tmp_path, "trace-e2e")
    cell = fold.trace_totals()["slowest"]
    assert cell is not None and cell[1] in ("q0", "q1", "q2")
    trace = trace_job(tmp_path, "trace-e2e", slowest=True)
    _assert_valid_chrome_trace(trace)
    names = [e["name"] for e in trace["traceEvents"] if e["ph"] == "X"]
    assert "request" in names and "prefill" in names
    assert names.count("decode") >= 1
    marks = [e["name"] for e in trace["traceEvents"] if e["ph"] == "i"]
    assert "admit" in marks and "retire" in marks
    # every submitted request is traceable, and dispatch ledgers match:
    # the root span's dispatch count equals its decode spans
    for i in range(3):
        t = trace_job(tmp_path, "trace-e2e", request=f"q{i}")
        xs = [e for e in t["traceEvents"] if e["ph"] == "X"]
        root = next(e for e in xs if e["name"] == "request")
        assert root["args"]["dispatches"] == sum(
            1 for e in xs if e["name"] == "decode"
        )


@pytest.mark.slow
def test_engine_warmup_not_traced(tmp_path, lm):
    import numpy as np

    from ddl_tpu.obs import EventWriter
    from ddl_tpu.obs.fold import fold_job
    from ddl_tpu.serve.engine import ServeEngine

    cfg, params, spec = lm
    obs = EventWriter(tmp_path, "warm-e2e")
    eng = ServeEngine(
        cfg, params, spec, block_size=8, num_blocks=32, max_batch=2,
        obs=obs,
    )
    eng.warmup(8, 2)
    eng.submit(np.arange(1, 6, dtype=np.int32), 3, request_id="real")
    eng.run()
    obs.close()
    fold = fold_job(tmp_path, "warm-e2e")
    tr = fold.trace_totals()
    # only the real request traced; the warmup must not win slowest
    assert tr["requests"] == 1
    assert tr["slowest"][1] == "real"
