"""Preemption handling: SIGTERM -> finish step -> checkpoint -> clean exit.

The reference has no preemption/failure handling (SURVEY.md §5); recovery
there is a manual job re-submit.  Here a real SIGTERM delivered mid-training
must produce a resumable snapshot and a clean return.
"""

import os
import signal
import threading


from ddl_tpu.checkpoint import latest_epoch
from ddl_tpu.config import Config, DataConfig, MeshConfig, ModelConfig, TrainConfig
from ddl_tpu.data import SyntheticAptosDataset
from ddl_tpu.utils.preemption import PreemptionGuard


def _tiny_cfg(tmp_path, epochs):
    cfg = Config(
        strategy="single",
        mesh=MeshConfig(1, 1),
        model=ModelConfig(
            growth_rate=4,
            block_config=(2, 2),
            num_init_features=8,
            bn_size=2,
            num_classes=5,
            split_blocks=(1,),
            compute_dtype="float32",
            remat=False,
        ),
        data=DataConfig(
            dataset_dir="",
            synthetic_num_train=64,
            synthetic_num_test=32,
            image_size=16,
            global_batch_size=16,
            eval_batch_size=16,
            num_workers=0,
        ),
        train=TrainConfig(
            max_epochs=epochs,
            save_best_qwk=False,
            async_checkpoint=False,
            log_dir=str(tmp_path / "logs"),
            checkpoint_dir=str(tmp_path / "ckpt"),
        ),
    )
    return cfg.validate()


def _datasets(cfg):
    return (
        SyntheticAptosDataset(cfg.data.synthetic_num_train, cfg.data.image_size, seed=1),
        SyntheticAptosDataset(cfg.data.synthetic_num_test, cfg.data.image_size, seed=2),
    )


def test_guard_flags_and_restores_handler():
    calls = []
    prev = signal.signal(signal.SIGTERM, lambda *a: calls.append(a))
    try:
        with PreemptionGuard() as guard:
            assert not guard.requested
            os.kill(os.getpid(), signal.SIGTERM)
            assert guard.requested
        # previous handler restored and reachable again
        os.kill(os.getpid(), signal.SIGTERM)
        assert len(calls) == 1
    finally:
        signal.signal(signal.SIGTERM, prev)


def test_lm_sigterm_checkpoints_and_resumes(tmp_path):
    """The flagship LM family survives preemption too: SIGTERM mid-window
    leaves a step-labelled resumable snapshot, and the relaunch continues
    the training stream from it (VERDICT round 2, task 1)."""
    import optax

    from ddl_tpu.checkpoint import latest_epoch as latest_step
    from ddl_tpu.models.transformer import LMConfig
    from ddl_tpu.parallel.sharding import LMMeshSpec
    from ddl_tpu.train.lm_trainer import LMRunConfig, LMTrainer

    # vocab covers the synthetic Markov byte stream (ids 0..255)
    cfg = LMConfig(
        vocab_size=256, d_model=32, n_layers=2, n_heads=4, head_dim=8,
        d_ff=64, compute_dtype="float32", remat=False,
    )

    def _run(steps, resume=None):
        return LMRunConfig(
            batch=4, seq_len=16, steps=steps, job_id="lm-preempt",
            checkpoint_dir=str(tmp_path / "ckpt"), save_every=10**9,
            resume_step=resume, log_dir=str(tmp_path / "logs"),
        )

    trainer = LMTrainer(cfg, LMMeshSpec(), optax.adam(1e-3), _run(10**6))
    timer = threading.Timer(1.0, os.kill, (os.getpid(), signal.SIGTERM))
    timer.start()
    try:
        trainer.train()  # returns instead of dying
    finally:
        timer.cancel()

    saved = latest_step(tmp_path / "ckpt", "lm-preempt")
    assert saved is not None and 0 < saved < 10**6
    assert saved == int(trainer.state.step)

    # relaunch with the SAME job id and no resume flag: auto-resume finds
    # the snapshot (VERDICT round 3, task 8 — relaunch == resume)
    resumed = LMTrainer(
        cfg, LMMeshSpec(), optax.adam(1e-3), _run(saved + 5)
    )
    assert resumed._start_step == saved
    resumed.train()
    assert int(resumed.state.step) == saved + 5

    # the explicit flag still works, and auto_resume=False starts fresh
    explicit = LMTrainer(
        cfg, LMMeshSpec(), optax.adam(1e-3), _run(saved + 5, resume=saved)
    )
    assert explicit._start_step == saved
    run_fresh = _run(10)
    run_fresh.auto_resume = False
    fresh = LMTrainer(cfg, LMMeshSpec(), optax.adam(1e-3), run_fresh)
    assert fresh._start_step == 0


def test_sigterm_mid_training_checkpoints_and_resumes(tmp_path, monkeypatch):
    from ddl_tpu.train import Trainer

    monkeypatch.setenv("DDL_JOB_ID", "preempt-test")
    cfg = _tiny_cfg(tmp_path, epochs=200)  # far more than can run pre-signal
    trainer = Trainer(cfg, datasets=_datasets(cfg))

    timer = threading.Timer(1.0, os.kill, (os.getpid(), signal.SIGTERM))
    timer.start()
    try:
        trainer.train()  # returns instead of dying
    finally:
        timer.cancel()

    assert 0 < trainer.epochs_run < 200
    saved = latest_epoch(cfg.train.checkpoint_dir, "preempt-test")
    assert saved == trainer.epochs_run - 1

    # relaunch with the same job id and NO resume flags: auto-resume picks
    # up the preemption snapshot (VERDICT round 3, task 8 — the
    # JobSet-restart story end to end).  If the signal landed mid-epoch,
    # the snapshot manifest carries a data cursor and the resumed run
    # re-enters THAT epoch at THAT batch (exact resume — no batch
    # replayed or skipped); a boundary snapshot resumes at the next one.
    from ddl_tpu.checkpoint import read_cursor

    cur = read_cursor(cfg.train.checkpoint_dir, "preempt-test", saved)
    cfg2 = _tiny_cfg(tmp_path, epochs=saved + 2)
    resumed = Trainer(cfg2, datasets=_datasets(cfg2))
    if cur and cur["offset"] > 0:
        assert resumed.epochs_run == cur["period"] == saved
        assert resumed._resume_offset == cur["offset"]
    else:
        assert resumed.epochs_run == saved + 1
    resumed.train()
    assert resumed.epochs_run == saved + 2

    # auto_resume=False opts back into a fresh start
    cfg3 = _tiny_cfg(tmp_path, epochs=1)
    cfg3.train.auto_resume = False
    fresh = Trainer(cfg3, datasets=_datasets(cfg3))
    assert fresh.epochs_run == 0
