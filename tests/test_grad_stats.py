"""Gradient-statistics observability (reference ddp.py:310-326 parity)."""

import jax
import numpy as np

from ddl_tpu.config import TrainConfig
from ddl_tpu.models import build_stages
from ddl_tpu.parallel.mesh import MeshSpec, build_mesh
from ddl_tpu.train.state import create_train_state, make_optimizer
from ddl_tpu.train.steps import make_grad_stats_fn
import jax.numpy as jnp


def test_grad_stats_values(tiny_model_cfg):
    stages = build_stages(tiny_model_cfg, num_stages=1)
    tx = make_optimizer(TrainConfig())
    state = create_train_state(stages, tx, jax.random.key(0), 16)
    mesh = build_mesh(MeshSpec(2, 1))
    fn = make_grad_stats_fn(stages, mesh, jnp.float32)

    rng = np.random.default_rng(0)
    images = rng.integers(0, 255, (4, 16, 16, 3)).astype(np.uint8)
    labels = rng.integers(0, 5, (4,)).astype(np.int32)
    stats = jax.device_get(fn(state, images, labels))

    assert any("classifier/kernel" in k for k in stats)
    for name, v in stats.items():
        assert v.shape == (7,)
        mn, mean, mx, p25, med, p75, std = v
        assert 0 <= mn <= p25 <= med <= p75 <= mx
        assert mn <= mean <= mx and std >= 0
    # classifier grads must be nonzero on a random batch
    k = next(k for k in stats if "classifier/kernel" in k)
    assert stats[k][2] > 0


def test_trainer_writes_gradient_csv(tmp_path):
    from tests.test_trainer import _datasets, _tiny_cfg
    from ddl_tpu.config import MeshConfig
    from ddl_tpu.train import Trainer

    cfg = _tiny_cfg(tmp_path, "single", MeshConfig(1, 1), epochs=1)
    cfg.train.log_gradient_stats = True
    trainer = Trainer(cfg, datasets=_datasets(cfg))
    trainer.train()
    lines = (tmp_path / "logs" / "gradient.csv").read_text().strip().splitlines()
    # 4 steps x n_params rows, 14 columns each (reference ddp.py:325)
    assert len(lines) > 0
    assert len(lines[0].split(",")) == 14
