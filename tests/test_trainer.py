"""End-to-end Trainer tests: epoch loop, CSV logs, QWK-gated checkpointing,
resume — on synthetic data over simulated meshes (all four strategies)."""

import numpy as np
import pytest

from ddl_tpu.config import Config, DataConfig, MeshConfig, ModelConfig, TrainConfig
from ddl_tpu.data import SyntheticAptosDataset
from ddl_tpu.utils.csv_logger import read_metric_csv


def _tiny_cfg(tmp_path, strategy, mesh, epochs=2):
    model = ModelConfig(
        growth_rate=4,
        block_config=(2, 2),
        num_init_features=8,
        bn_size=2,
        num_classes=5,
        split_blocks=(1,),
        compute_dtype="float32",
        remat=False,
    )
    cfg = Config(
        strategy=strategy,
        mesh=mesh,
        model=model,
        data=DataConfig(
            dataset_dir="",
            synthetic_num_train=64,
            synthetic_num_test=32,
            image_size=16,
            global_batch_size=16,
            eval_batch_size=16,
            num_workers=0,
        ),
        train=TrainConfig(
            max_epochs=epochs,
            num_microbatches=2,
            log_dir=str(tmp_path / "logs"),
            checkpoint_dir=str(tmp_path / "ckpt"),
        ),
    )
    return cfg.validate()


def _datasets(cfg):
    return (
        SyntheticAptosDataset(cfg.data.synthetic_num_train, cfg.data.image_size, seed=1),
        SyntheticAptosDataset(cfg.data.synthetic_num_test, cfg.data.image_size, seed=2),
    )


STRATEGIES = [
    ("single", MeshConfig(1, 1)),
    ("dp", MeshConfig(4, 1)),
    ("pp", MeshConfig(1, 2)),
    ("dp_pp", MeshConfig(2, 2)),
]


@pytest.mark.parametrize("strategy,mesh", STRATEGIES)
def test_trainer_end_to_end(tmp_path, strategy, mesh):
    from ddl_tpu.train import Trainer

    cfg = _tiny_cfg(tmp_path, strategy, mesh)
    trainer = Trainer(cfg, datasets=_datasets(cfg))
    trainer.train()

    job_dir = trainer.logger.job_dir
    # the full reference metric suite is logged every epoch (single.py:187-189,244-251)
    for metric in (
        "loss",
        "train_accuracy",
        "epoch_time",
        "val_loss",
        "val_accuracy",
        "macro_f1",
        "weighted_f1",
        "macro_precision",
        "weighted_precision",
        "macro_recall",
        "weighted_recall",
        "qwk",
    ):
        rows = read_metric_csv(job_dir / f"{metric}.csv")
        assert [r["epoch"] for r in rows] == [0, 1], metric
        assert all(np.isfinite(r["value"]) for r in rows)
    # QWK-gated snapshot saved at least once
    ckpt_dir = trainer.logger.job_dir  # logs dir; checkpoints separate:
    from ddl_tpu.checkpoint import latest_epoch

    assert latest_epoch(cfg.train.checkpoint_dir, trainer.job_id) is not None


def test_eval_full_coverage_and_epoch_invariant(tmp_path):
    """Eval counts every test sample exactly once and is deterministic
    across epochs (the SPMD analog of the reference evaluating everything,
    single.py:199-258) — round 1 evaluated a per-epoch-reshuffled subset,
    which made the QWK save gate noisy by construction."""
    from ddl_tpu.train import Trainer

    cfg = _tiny_cfg(tmp_path, "single", MeshConfig(1, 1))
    cfg.data.synthetic_num_test = 29  # not divisible by eval_batch_size=16
    trainer = Trainer(cfg, datasets=_datasets(cfg))
    m0 = trainer.evaluate(0)
    m5 = trainer.evaluate(5)
    assert m0["val_examples"] == 29.0  # full coverage, padding masked out
    assert m0 == m5  # epoch-order invariant


def test_resume_from_snapshot(tmp_path):
    from ddl_tpu.checkpoint import latest_epoch
    from ddl_tpu.train import Trainer

    cfg = _tiny_cfg(tmp_path, "single", MeshConfig(1, 1), epochs=2)
    t1 = Trainer(cfg, datasets=_datasets(cfg))
    t1.train()
    saved = latest_epoch(cfg.train.checkpoint_dir, t1.job_id)
    assert saved is not None

    cfg2 = _tiny_cfg(tmp_path, "single", MeshConfig(1, 1), epochs=4)
    cfg2.train.snapshot_job_id = t1.job_id
    cfg2.train.snapshot_epoch = saved
    t2 = Trainer(cfg2, datasets=_datasets(cfg2))
    assert t2.epochs_run == saved + 1  # resume semantics (single.py:124)
    # resumed state carries the trained params (loss should not reset)
    t2.train()
    assert t2.epochs_run == 4


def test_state_roundtrip(tmp_path, tiny_model_cfg):
    """Checkpoint save/load restores the exact pytree."""
    import jax

    from ddl_tpu import checkpoint as ckpt
    from ddl_tpu.config import TrainConfig as TC
    from ddl_tpu.models import build_stages
    from ddl_tpu.train.state import create_train_state, make_optimizer

    stages = build_stages(tiny_model_cfg)
    tx = make_optimizer(TC())
    state = create_train_state(stages, tx, jax.random.key(0), 16)
    ckpt.save_snapshot(tmp_path / "ck", "job", 3, state)
    restored, epochs_run = ckpt.load_snapshot(tmp_path / "ck", "job", 3, state)
    assert epochs_run == 4
    for a, b in zip(jax.tree.leaves(state), jax.tree.leaves(restored)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
