"""ZeRO-1 weight-update sharding (train/fused_optim + the step
factories): multi-step trajectory parity against the replicated fused
Adam for every family, actual moment placement and per-device byte
reduction, grace-window (scale_tx) preservation, both optimizer
endpoints, replicated<->sharded checkpoint interop, and the
opt_hbm_bytes obs gauge.
"""

import math

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from ddl_tpu.parallel import rules as R
from ddl_tpu.train.fused_optim import ZeroConfig, fused_adam, with_zero

STEPS = 4
TOL = 1e-6


def _per_device_bytes(tree) -> int:
    total = 0
    for leaf in jax.tree.leaves(tree):
        sharding = getattr(leaf, "sharding", None)
        shape = sharding.shard_shape(leaf.shape) if sharding else leaf.shape
        total += math.prod(shape) * leaf.dtype.itemsize
    return total


def _data_sharded(leaf) -> bool:
    spec = getattr(leaf.sharding, "spec", None)
    return spec is not None and "data" in R.spec_axes(spec)


def _max_diff(a, b) -> float:
    return max(
        float(jnp.max(jnp.abs(x.astype(jnp.float32) - y.astype(jnp.float32))))
        for x, y in zip(jax.tree.leaves(a), jax.tree.leaves(b))
    )


# ---------------------------------------------------------------------------
# CNN family (data=4): trajectory parity + placement + byte reduction
# ---------------------------------------------------------------------------


def _cnn_setup():
    from ddl_tpu.config import ModelConfig
    from ddl_tpu.models import build_stages
    from ddl_tpu.parallel.mesh import MeshSpec, build_mesh

    cfg = ModelConfig(
        growth_rate=4, block_config=(2, 2), num_init_features=8, bn_size=2,
        num_classes=5, split_blocks=(1,), compute_dtype="float32",
        remat=False,
    )
    mesh = build_mesh(MeshSpec(data=4))
    stages = build_stages(cfg, num_stages=1)
    rng = np.random.default_rng(0)
    imgs = jnp.asarray(rng.integers(0, 255, (8, 16, 16, 3)), jnp.uint8)
    lbls = jnp.asarray(rng.integers(0, 5, (8,)), jnp.int32)
    return stages, mesh, imgs, lbls


def _cnn_run(stages, mesh, imgs, lbls, zero: bool, scale: float = 1.0):
    from ddl_tpu.train.recovery import scale_tx
    from ddl_tpu.train.state import create_train_state
    from ddl_tpu.train.steps import make_dp_step_fns

    tx = fused_adam(1e-3)
    if zero:
        # probe-sized model: a small threshold exercises the sharded
        # expression on the same leaves a real model shards at 8192
        tx = with_zero(tx, mesh, threshold=64)
    tx = scale_tx(tx, scale)
    state = create_train_state(
        stages, tx, jax.random.key(0), 16, mesh=mesh if zero else None
    )
    fns = make_dp_step_fns(stages, tx, mesh, jnp.float32)
    for _ in range(STEPS):
        state, loss, _ = fns.train(state, imgs, lbls)
    return state, float(loss), fns


def test_cnn_zero_trajectory_matches_replicated():
    stages, mesh, imgs, lbls = _cnn_setup()
    s_rep, loss_rep, _ = _cnn_run(stages, mesh, imgs, lbls, zero=False)
    s_z, loss_z, fns = _cnn_run(stages, mesh, imgs, lbls, zero=True)
    assert _max_diff(s_rep.params, s_z.params) <= TOL
    assert abs(loss_rep - loss_z) <= TOL
    assert _max_diff(s_rep.opt_state[0].mu, s_z.opt_state[0].mu) <= TOL
    assert fns.train.contract["zero_sharding"] is True
    # every >=threshold moment leaf actually lives data-sharded, and the
    # per-device bytes drop toward 1/dp
    big = [
        leaf for leaf in jax.tree.leaves(s_z.opt_state[0].mu)
        if leaf.size >= 64 and any(d % 4 == 0 for d in leaf.shape)
    ]
    assert big and all(_data_sharded(leaf) for leaf in big)
    rep_bytes = _per_device_bytes(s_rep.opt_state)
    z_bytes = _per_device_bytes(s_z.opt_state)
    assert z_bytes < rep_bytes / 2  # most leaves eligible in this config


def test_cnn_zero_grace_window_scale_preserved():
    """scale_tx must rebuild (not wrap) the fused Adam: the grace run
    keeps ZeRO placement AND matches the replicated grace run."""
    stages, mesh, imgs, lbls = _cnn_setup()
    s_rep, _, _ = _cnn_run(stages, mesh, imgs, lbls, zero=False, scale=0.1)
    s_z, _, _ = _cnn_run(stages, mesh, imgs, lbls, zero=True, scale=0.1)
    # slightly looser than TOL: the scaled update perturbs f32 rounding,
    # and the reduce-scatter/all-reduce order difference feeds back
    # through the BN batch statistics over the 4 steps
    assert _max_diff(s_rep.params, s_z.params) <= 1e-5
    big = [
        leaf for leaf in jax.tree.leaves(s_z.opt_state[0].mu)
        if leaf.size >= 64 and any(d % 4 == 0 for d in leaf.shape)
    ]
    assert big and all(_data_sharded(leaf) for leaf in big)


# ---------------------------------------------------------------------------
# LM family (data=4; real 8192 threshold crosses the probe model's
# vocab/MLP kernels) + checkpoint interop
# ---------------------------------------------------------------------------


def _lm_fns(zero: bool, data: int = 4, model: int = 1):
    from ddl_tpu.models.transformer import LMConfig
    from ddl_tpu.parallel.sharding import LMMeshSpec
    from ddl_tpu.train.lm_steps import make_lm_step_fns

    cfg = LMConfig(
        vocab_size=512, d_model=64, n_layers=2, n_heads=4, head_dim=16,
        d_ff=256, compute_dtype="float32",
    )
    return make_lm_step_fns(
        cfg, LMMeshSpec(data=data, model=model), fused_adam(1e-3),
        jax.random.key(0), batch=8, seq_len=32, zero_sharding=zero,
    )


def _lm_batch():
    rng = np.random.default_rng(0)
    inp = jnp.asarray(rng.integers(0, 512, (8, 32)), jnp.int32)
    tgt = jnp.asarray(rng.integers(0, 512, (8, 32)), jnp.int32)
    return inp, tgt


@pytest.mark.parametrize("model", [1, 2])
def test_lm_zero_trajectory_matches_replicated(model):
    inp, tgt = _lm_batch()
    data = 4 if model == 1 else 2

    def run(zero):
        fns = _lm_fns(zero, data=data, model=model)
        state = fns.init_state()
        for _ in range(STEPS):
            state, m = fns.train(state, inp, tgt)
        return state, float(m["loss"]), fns

    s_rep, loss_rep, _ = run(False)
    s_z, loss_z, fns = run(True)
    assert _max_diff(s_rep.params, s_z.params) <= TOL
    assert abs(loss_rep - loss_z) <= TOL
    # every >=8192-element leaf's moments carry 'data'
    checked = 0
    for p_leaf, mu_leaf in zip(
        jax.tree.leaves(s_z.params), jax.tree.leaves(s_z.opt_state[0].mu)
    ):
        if p_leaf.size >= R.ZERO_THRESHOLD:
            checked += 1
            assert _data_sharded(mu_leaf), p_leaf.shape
    assert checked >= 4
    assert _per_device_bytes(s_z.opt_state) < _per_device_bytes(s_rep.opt_state)
    assert fns.train.contract["zero_sharding"] is True
    assert fns.train.contract["fused_optimizer_update"] is True


def test_lm_zero_checkpoint_round_trip(tmp_path):
    """Replicated-era snapshots restore into a ZeRO layout and vice
    versa (Orbax global arrays; the abstract state carries the target
    shardings), values bit-identical either way."""
    from ddl_tpu import checkpoint as ckpt

    inp, tgt = _lm_batch()
    fns_rep = _lm_fns(False)
    state = fns_rep.init_state()
    for _ in range(2):
        state, _m = fns_rep.train(state, inp, tgt)
    ckpt.save_snapshot(tmp_path, "job", 0, state)

    # replicated snapshot -> ZeRO-sharded live state
    fns_z = _lm_fns(True)
    target = fns_z.init_state()
    restored, _ = ckpt.load_snapshot(tmp_path, "job", 0, target)
    assert _max_diff(state.params, restored.params) == 0.0
    assert _max_diff(state.opt_state[0].mu, restored.opt_state[0].mu) == 0.0
    big_mu = [
        m for p, m in zip(jax.tree.leaves(restored.params),
                          jax.tree.leaves(restored.opt_state[0].mu))
        if p.size >= R.ZERO_THRESHOLD
    ]
    assert big_mu and all(_data_sharded(m) for m in big_mu)

    # continue training from the restored ZeRO state and save SHARDED
    restored, _m = fns_z.train(restored, inp, tgt)
    ckpt.save_snapshot(tmp_path, "job", 1, restored)

    # sharded snapshot -> replicated live state
    back, _ = ckpt.load_snapshot(tmp_path, "job", 1, fns_rep.init_state())
    assert all(
        leaf.sharding.is_fully_replicated
        for leaf in jax.tree.leaves(back.opt_state[0].mu)
    )
    # ...and it equals a pure-replicated continuation of the same step
    cont = state
    cont, _m2 = fns_rep.train(cont, inp, tgt)
    assert _max_diff(cont.params, back.params) <= TOL
    assert _max_diff(cont.opt_state[0].mu, back.opt_state[0].mu) <= TOL


def test_load_snapshot_shardings_override_reshards(tmp_path):
    """checkpoint.load_snapshot(shardings=...) restores straight into
    rule placement — the rule-driven shard-on-load path."""
    from ddl_tpu import checkpoint as ckpt
    from ddl_tpu.parallel.sharding import LMMeshSpec, build_lm_mesh

    fns = _lm_fns(False, data=2, model=2)
    state = fns.init_state()
    ckpt.save_snapshot(tmp_path, "job", 0, state)
    mesh = build_lm_mesh(LMMeshSpec(data=2, model=2))
    shardings = ckpt.state_rule_shardings(state, R.lm_rules(), mesh)
    restored, _ = ckpt.load_snapshot(
        tmp_path, "job", 0, state, shardings=shardings
    )
    head = restored.params["lm_head"]["kernel"]
    assert "model" in R.spec_axes(head.sharding.spec)
    mu_head = restored.opt_state[0].mu["lm_head"]["kernel"]
    assert "model" in R.spec_axes(mu_head.sharding.spec)


def test_zero_snapshot_reshards_across_data_axis_grow(tmp_path):
    """The elastic scale-UP re-shard contract (round 24): ZeRO-1
    moments saved sharded over a dp=2 data axis restore BIT-IDENTICALLY
    into a dp=4 ZeRO layout (the grow epoch's larger world) — and a
    dp=4-sharded snapshot restores back down onto dp=2.  The re-shard
    is checkpoint.load_snapshot's global-array restore resolving the
    target's shardings; no gather/scatter pass of its own, which is
    exactly why the grow path routes through a snapshot restore."""
    from ddl_tpu import checkpoint as ckpt

    inp, tgt = _lm_batch()
    fns_small = _lm_fns(True, data=2)
    state = fns_small.init_state()
    for _ in range(2):
        state, _m = fns_small.train(state, inp, tgt)
    ckpt.save_snapshot(tmp_path, "job", 0, state)

    # dp=2-sharded snapshot -> dp=4 ZeRO live state (the grow epoch).
    # Comparisons go through device_get: the two states live on
    # different device SETS (2 vs 4 CPUs), which jnp ops refuse to mix.
    fns_big = _lm_fns(True, data=4)
    grown, _ = ckpt.load_snapshot(tmp_path, "job", 0, fns_big.init_state())
    host = jax.device_get
    assert _max_diff(host(state.params), host(grown.params)) == 0.0
    assert _max_diff(
        host(state.opt_state[0].mu), host(grown.opt_state[0].mu)
    ) == 0.0
    assert _max_diff(
        host(state.opt_state[0].nu), host(grown.opt_state[0].nu)
    ) == 0.0
    # ...and the moments actually LIVE sharded over the larger axis
    big_mu = [
        m for p, m in zip(jax.tree.leaves(grown.params),
                          jax.tree.leaves(grown.opt_state[0].mu))
        if p.size >= R.ZERO_THRESHOLD
    ]
    assert big_mu and all(_data_sharded(m) for m in big_mu)

    # the grown world trains on and saves dp=4-sharded; a later shrink
    # restores that straight back onto the dp=2 layout
    grown, _m = fns_big.train(grown, inp, tgt)
    ckpt.save_snapshot(tmp_path, "job", 1, grown)
    back, _ = ckpt.load_snapshot(tmp_path, "job", 1, fns_small.init_state())
    assert _max_diff(host(grown.params), host(back.params)) == 0.0
    assert _max_diff(
        host(grown.opt_state[0].mu), host(back.opt_state[0].mu)
    ) == 0.0
    assert _max_diff(
        host(grown.opt_state[0].nu), host(back.opt_state[0].nu)
    ) == 0.0


# ---------------------------------------------------------------------------
# ViT family + optimizer endpoints + misc wiring
# ---------------------------------------------------------------------------


def test_vit_zero_trajectory_matches_replicated():
    from ddl_tpu.models.vit import ViTConfig
    from ddl_tpu.parallel.sharding import LMMeshSpec
    from ddl_tpu.train.vit_steps import make_vit_step_fns

    cfg = ViTConfig(
        image_size=16, patch_size=8, d_model=64, n_layers=2, n_heads=4,
        head_dim=16, d_ff=256, compute_dtype="float32", remat=False,
    )
    rng = np.random.default_rng(0)
    imgs = jnp.asarray(rng.integers(0, 255, (8, 16, 16, 3)), jnp.uint8)
    lbls = jnp.asarray(rng.integers(0, 5, (8,)), jnp.int32)

    def run(zero):
        fns = make_vit_step_fns(
            cfg, LMMeshSpec(data=4), fused_adam(1e-3), jax.random.key(0),
            batch=8, zero_sharding=zero,
        )
        state = fns.init_state()
        for _ in range(STEPS):
            state, m = fns.train(state, imgs, lbls)
        return state, float(m["loss"])

    s_rep, loss_rep = run(False)
    s_z, loss_z = run(True)
    assert _max_diff(s_rep.params, s_z.params) <= TOL
    assert abs(loss_rep - loss_z) <= TOL
    big_mu = [
        m for p, m in zip(jax.tree.leaves(s_z.params),
                          jax.tree.leaves(s_z.opt_state[0].mu))
        if p.size >= R.ZERO_THRESHOLD
    ]
    assert big_mu and all(_data_sharded(m) for m in big_mu)


def test_update_endpoint_matches_fused_apply_under_zero():
    """The optax-style two-pass path (recovery grace fallback, pipeline
    callers) must emit the same update as fused_apply, gathered back to
    the parameter placement."""
    import optax

    from ddl_tpu.parallel.sharding import LMMeshSpec, build_lm_mesh

    mesh = build_lm_mesh(LMMeshSpec(data=4))
    params = {"w": jnp.arange(64.0 * 256).reshape(64, 256) / 1e4}
    grads = {"w": jnp.ones((64, 256)) * 0.01}
    zero = ZeroConfig(mesh=mesh, param_specs={"w": P()}, threshold=64)
    tx = fused_adam(1e-3, zero=zero)
    state = tx.init(params)
    assert _data_sharded(state[0].mu["w"])

    @jax.jit
    def two_pass(grads, state, params):
        updates, new_state = tx.update(grads, state, params)
        return optax.apply_updates(params, updates), new_state

    @jax.jit
    def one_pass(grads, state, params):
        return tx.fused_apply(grads, state, params)

    p2, s2 = two_pass(grads, state, params)
    p1, s1 = one_pass(grads, state, params)
    assert _max_diff(p1, p2) <= TOL
    assert _max_diff(s1[0].mu, s2[0].mu) == 0.0
    # against plain optax.adam math
    ref = optax.adam(1e-3)
    ur, _sr = ref.update(grads, ref.init(params), params)
    pr = optax.apply_updates(params, ur)
    assert _max_diff(p1, pr) <= TOL


def test_with_zero_validation():
    import optax

    from ddl_tpu.parallel.sharding import LMMeshSpec, build_lm_mesh

    mesh = build_lm_mesh(LMMeshSpec(data=4))
    # non-fused transformations are a loud error
    with pytest.raises(ValueError, match="fused Adam"):
        with_zero(optax.adam(1e-3), mesh)
    # dp=1 is a no-op, whatever the tx
    mesh1 = build_lm_mesh(LMMeshSpec(data=1, model=2))
    tx = optax.adam(1e-3)
    assert with_zero(tx, mesh1) is tx
    # pipeline paths refuse zero_sharding
    from ddl_tpu.models.transformer import LMConfig
    from ddl_tpu.train.lm_steps import make_lm_step_fns

    with pytest.raises(ValueError, match="non-pipelined"):
        make_lm_step_fns(
            LMConfig(vocab_size=64, d_model=16, n_layers=2, n_heads=2,
                     head_dim=8, d_ff=32, compute_dtype="float32"),
            LMMeshSpec(data=2, pipe=2), fused_adam(1e-3),
            jax.random.key(0), batch=8, seq_len=16, num_microbatches=2,
            zero_sharding=True,
        )


def test_train_config_zero_validation():
    from ddl_tpu.config import preset

    with pytest.raises(ValueError, match="zero_sharding"):
        preset("dp_pp", **{"train.zero_sharding": True})
    with pytest.raises(ValueError, match="fused_adam"):
        preset("dp", **{"train.zero_sharding": True,
                        "train.fused_adam": False})
    # weight decay / clipping route make_optimizer to the optax chain
    # even with fused_adam=true — validate() must catch them up front
    with pytest.raises(ValueError, match="weight_decay"):
        preset("dp", **{"train.zero_sharding": True,
                        "train.weight_decay": 0.05})
    with pytest.raises(ValueError, match="grad_clip_norm"):
        preset("dp", **{"train.zero_sharding": True,
                        "train.grad_clip_norm": 1.0})
    cfg = preset("dp", **{"train.zero_sharding": True})
    assert cfg.train.zero_sharding is True


def test_opt_hbm_bytes_gauge_flows_to_export(tmp_path):
    """The loop stamps opt_hbm_bytes into period rates; the fold stores
    it per (host, repoch) and `obs export` renders the gauge."""
    from ddl_tpu.obs.events import EventWriter
    from ddl_tpu.obs.export import prometheus_text
    from ddl_tpu.obs.fold import fold_job

    w = EventWriter(tmp_path, "zjob", host=0)
    w.emit(
        "period", step=10, period=0, steps=10, elapsed=2.0,
        steps_per_sec=5.0, phases={"step": 1.5}, loss=1.0, compiles=0,
        rates={"mfu": 0.2, "opt_hbm_bytes": 123456},
    )
    w.close()
    fold = fold_job(tmp_path, "zjob", cache=False)
    text = prometheus_text(fold, "zjob")
    assert "ddl_obs_opt_hbm_bytes" in text
    assert "123456" in text
    assert 'job_id="zjob"' in text


def test_loop_opt_state_hbm_measures_shards():
    """BaseTrainer.opt_state_hbm_bytes reads live shard shapes — a
    ZeRO-sharded state reports ~1/dp of the replicated bytes."""
    from ddl_tpu.train.loop import BaseTrainer

    stages, mesh, imgs, lbls = _cnn_setup()
    s_rep, _, _ = _cnn_run(stages, mesh, imgs, lbls, zero=False)
    s_z, _, _ = _cnn_run(stages, mesh, imgs, lbls, zero=True)

    class T(BaseTrainer):
        def __init__(self, state):
            self.state = state

    rep = T(s_rep).opt_state_hbm_bytes()
    z = T(s_z).opt_state_hbm_bytes()
    assert rep == _per_device_bytes(s_rep.opt_state)
    assert z == _per_device_bytes(s_z.opt_state)
    assert z < rep / 2
