"""Int8 tiny-M matmul kernel (ops/int8_matvec.py) vs the XLA reference,
both weight layouts, interpreter mode.  The kernel is a recorded
NEGATIVE experiment (measured slower than XLA's lowering, PERF.md
round 5) and is not wired into the model — the tests keep the artifact
honest."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ddl_tpu.ops.int8_matvec import MATVEC_MAX_ROWS, int8_matmul_small_m


@pytest.mark.parametrize("m", [1, 3, 8])
@pytest.mark.parametrize("contract_last", [False, True],
                         ids=["DxO", "OxD"])
def test_matches_xla_reference(m, contract_last):
    rng = np.random.default_rng(0)
    d, o = 64, 384
    x = jnp.asarray(rng.normal(size=(m, d)), jnp.float32)
    w8 = jnp.asarray(rng.integers(-127, 127, (o, d) if contract_last
                                  else (d, o)), jnp.int8)
    scale = jnp.asarray(rng.random((1, o)) * 0.01, jnp.float32)
    got = int8_matmul_small_m(
        x, w8, scale, contract_last=contract_last, block_o=128,
        interpret=True,
    )
    wf = w8.astype(jnp.float32)
    want = (x @ (wf.T if contract_last else wf)) * scale
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), atol=1e-3, rtol=1e-4
    )
    assert got.shape == (m, o)


def test_rejects_large_m():
    x = jnp.zeros((MATVEC_MAX_ROWS + 1, 16), jnp.float32)
    w8 = jnp.zeros((16, 32), jnp.int8)
    with pytest.raises(ValueError, match="use the XLA path"):
        int8_matmul_small_m(x, w8, jnp.ones((1, 32)), interpret=True)
