"""HBM ledger (obs/hbm.py): the exhaustive per-device memory account.

The load-bearing properties pinned here:

- **sums-to-total**: per-(host, repoch) category bytes sum EXACTLY to
  the sampled watermark — ``untracked`` is the reported residual,
  never dropped (negative when tracking over-counts: still honest).
- **paired max cell**: the account's categories are the ones observed
  AT the peak-watermark sample, not independent per-category maxima.
- **plan vs live**: ``plan_program`` stamps a static budget (aval
  arithmetic always; the compiled executable's own memory analysis in
  full mode) that the reducer retains per label.
- **OOM forensics**: ``dump_oom`` writes a final snapshot the account
  renders after the process dies.
- **the leak gate**: an injected leak (``DDL_FAULT=leak@step``) grows
  the synthetic watermark on CPU, and ``obs diff --fail-hbm-growth``
  exits nonzero against a clean baseline — the CI wiring for "this PR
  leaks device memory".
"""

import json

import pytest


def _ev(host, kind, ts, **kw):
    e = {
        "ts": ts, "mono": ts, "run": f"r{host}", "host": host,
        "step": kw.pop("step", None), "kind": kind,
    }
    e.update(kw)
    return e


def _append(log_dir, job, host, lines):
    d = log_dir / "by_job_id" / job
    d.mkdir(parents=True, exist_ok=True)
    with open(d / f"events-h{host:03d}.jsonl", "a") as f:
        for ln in lines:
            f.write(ln + "\n")


def _fold(log_dir, job):
    from ddl_tpu.obs.fold import fold_job

    return fold_job(log_dir, job, cache=False)


# ---------------------------------------------------------------------------
# the account
# ---------------------------------------------------------------------------


def test_account_sums_to_watermark_bucket_exact(tmp_path):
    """Synthetic two-sample stream: the account carries the PEAK
    sample's categories, and every category (untracked included) sums
    byte-exactly to that sample's watermark."""
    from ddl_tpu.obs.hbm import CATEGORIES, account_from_fold

    evs = [
        _ev(0, "run_start", 1.0),
        # early sample: higher opt bytes, lower watermark — must NOT
        # leak into the peak cell (paired max, not per-category max)
        _ev(0, "hbm_sample", 2.0, params_bytes=500, opt_bytes=9999,
            watermark=11000, peak=11000, limit=50000, synthetic=True),
        _ev(0, "hbm_sample", 3.0, params_bytes=600, opt_bytes=1200,
            kv_cached_bytes=64, kv_private_bytes=32, kv_free_bytes=128,
            watermark=12345, peak=12400, limit=50000, synthetic=True),
        _ev(0, "run_end", 4.0),
    ]
    _append(tmp_path, "acct", 0, [json.dumps(e) for e in evs])
    account = account_from_fold(_fold(tmp_path, "acct"))
    assert len(account["incarnations"]) == 1
    inc = account["incarnations"][0]
    assert inc["watermark"] == 12345
    # paired max cell: the peak sample's categories, not the maxima
    assert inc["bytes"]["optimizer"] == 1200
    assert inc["bytes"]["params"] == 600
    assert inc["bytes"]["kv_cached"] == 64
    # exhaustive: every category sums exactly to the watermark
    assert set(inc["bytes"]) == set(CATEGORIES)
    assert sum(inc["bytes"].values()) == inc["watermark"]
    assert inc["bytes"]["untracked"] == 12345 - (600 + 1200 + 64 + 32 + 128)
    assert inc["headroom"] == 50000 - 12345
    # job row over one host == that host's latest incarnation
    assert account["job"]["watermark"] == 12345
    assert account["job"]["peak_bytes"] == 12345
    assert sum(account["job"]["bytes"].values()) == 12345


def test_account_untracked_negative_is_reported(tmp_path):
    """Tracked bytes exceeding the watermark (double-booked category or
    allocator slack) must surface as a NEGATIVE untracked residual, not
    be clamped away — the reconciliation is only trustworthy if it is
    allowed to say 'the books don't balance'."""
    from ddl_tpu.obs.hbm import account_from_fold

    evs = [
        _ev(0, "hbm_sample", 2.0, params_bytes=900, opt_bytes=300,
            watermark=1000, peak=1000, synthetic=True),
    ]
    _append(tmp_path, "neg", 0, [json.dumps(e) for e in evs])
    inc = account_from_fold(_fold(tmp_path, "neg"))["incarnations"][0]
    assert inc["bytes"]["untracked"] == -200
    assert sum(inc["bytes"].values()) == 1000


def test_account_job_row_sums_latest_repoch_per_host(tmp_path):
    """A restarted host's repoch-1 memory REPLACES its repoch-0 memory
    on the same device — the job row sums each host's latest repoch
    (summing both would double-book the device), while the headline
    peak is the max watermark ever sampled anywhere."""
    from ddl_tpu.obs.hbm import account_from_fold

    evs = [
        _ev(0, "hbm_sample", 2.0, params_bytes=700, opt_bytes=0,
            watermark=900, peak=900, synthetic=True),
        _ev(0, "hbm_sample", 5.0, params_bytes=500, opt_bytes=0,
            watermark=600, peak=600, synthetic=True, repoch=1),
    ]
    _append(tmp_path, "repo", 0, [json.dumps(e) for e in evs])
    account = account_from_fold(_fold(tmp_path, "repo"))
    assert len(account["incarnations"]) == 2
    assert account["job"]["watermark"] == 600  # latest repoch only
    assert account["job"]["peak_bytes"] == 900  # headline: ever-max


def test_render_hbm_shows_plans_and_oom(tmp_path):
    """The rendered account: category table, plan table, OOM line."""
    from ddl_tpu.obs.hbm import account_from_fold, render_hbm

    evs = [
        _ev(0, "hbm_plan", 1.5, label="train_step", analysis="compiled",
            argument_bytes=4096, output_bytes=4096, temp_bytes=512,
            alias_bytes=4000, code_bytes=64),
        _ev(0, "hbm_sample", 2.0, params_bytes=600, opt_bytes=1200,
            watermark=2000, peak=2000, limit=4096, synthetic=True),
        _ev(0, "hbm_oom_dump", 3.0, step=7,
            error="RESOURCE_EXHAUSTED: out of memory", watermark=4000,
            limit=4096,
            buffers=[{"shape": [64, 64], "dtype": "float32",
                      "count": 2, "bytes": 32768}]),
    ]
    _append(tmp_path, "rend", 0, [json.dumps(e) for e in evs])
    out = render_hbm(account_from_fold(_fold(tmp_path, "rend")), "rend")
    assert "optimizer" in out and "untracked" in out
    assert "train_step" in out and "static plans" in out
    assert "OOM forensics: 1 dump(s)" in out
    assert "float32[64x64] x2" in out
    assert "synthetic watermark" in out  # CPU watermarks must say so


# ---------------------------------------------------------------------------
# emission: live_sample / plan_program / dump_oom through a real writer
# ---------------------------------------------------------------------------


def test_live_sample_plan_and_oom_roundtrip(tmp_path):
    """Emit through a real EventWriter and fold the stream back: the
    synthetic watermark equals the tracked sum (no leak active), the
    full-mode plan carries the compiled executable's temp bytes, and
    the OOM dump books live buffers."""
    import jax
    import jax.numpy as jnp

    from ddl_tpu.obs import hbm
    from ddl_tpu.obs.events import EventWriter
    from ddl_tpu.obs.hbm import account_from_fold

    w = EventWriter(tmp_path, "rt", host=0)
    e = hbm.live_sample(
        w, params_bytes=1000, opt_bytes=2000, kv_free_bytes=500,
    )
    assert e["synthetic"] is True
    assert e["watermark"] == 3500

    fn = jax.jit(lambda x: x * 2 + 1)
    x = jnp.zeros((128, 128), jnp.float32)
    fn(x)  # dispatch once, like the trainers (plan after first step)
    plan = hbm.plan_program(w, "double", fn, (x,))
    assert plan is not None
    assert plan["analysis"] == "memory_analysis"
    assert plan["argument_bytes"] == x.nbytes
    assert plan["output_bytes"] == x.nbytes
    assert plan["temp_bytes"] is not None

    err = RuntimeError("RESOURCE_EXHAUSTED: Out of memory allocating")
    assert hbm.is_oom_error(err)
    assert not hbm.is_oom_error(ValueError("shape mismatch"))
    dump = hbm.dump_oom(w, err, step=3, params_bytes=1000, opt_bytes=2000)
    assert dump is not None and dump["buffers"]
    w.close()

    account = account_from_fold(_fold(tmp_path, "rt"))
    inc = account["incarnations"][0]
    assert inc["watermark"] == 3500
    assert sum(inc["bytes"].values()) == 3500
    assert inc["plans"]["double"]["analysis"] == "memory_analysis"
    assert inc["oom_count"] == 1
    assert inc["oom"]["error"].startswith("RESOURCE_EXHAUSTED")


def test_plan_program_aval_mode_never_compiles(tmp_path):
    """DDL_HBM_PLAN=aval's budget: shape arithmetic only — argument and
    output bytes filled, temp/code honestly absent."""
    import jax
    import jax.numpy as jnp

    from ddl_tpu.obs import hbm
    from ddl_tpu.obs.events import EventWriter

    w = EventWriter(tmp_path, "aval", host=0)
    fn = jax.jit(lambda x: x + 1)
    x = jnp.zeros((64,), jnp.float32)
    plan = hbm.plan_program(w, "inc", fn, (x,), mode="aval")
    w.close()
    assert plan["analysis"] == "aval"
    assert plan["argument_bytes"] == x.nbytes
    assert plan["output_bytes"] == x.nbytes
    assert plan["temp_bytes"] is None


def test_tree_shard_bytes_counts_per_shard(tmp_path):
    """Unsharded arrays: per-shard bytes == nbytes; empty trees are
    None (a serving engine with no params must not book a zero row)."""
    import jax.numpy as jnp

    from ddl_tpu.obs.hbm import tree_shard_bytes

    tree = {"a": jnp.zeros((8, 8), jnp.float32), "b": jnp.zeros((4,))}
    assert tree_shard_bytes(tree) == 8 * 8 * 4 + 4 * 4
    assert tree_shard_bytes(None) is None
    assert tree_shard_bytes({}) is None


def test_tree_shard_bytes_reflects_sharding():
    """The ZeRO measurement contract: a leaf sharded 8-way books 1/8 of
    its global bytes per device — the optimizer row of a --zero run
    must show the saving, not the global size."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh, NamedSharding, PartitionSpec

    from ddl_tpu.obs.hbm import tree_shard_bytes

    mesh = Mesh(jax.devices()[:8], ("data",))
    x = jax.device_put(
        jnp.zeros((64, 16), jnp.float32),
        NamedSharding(mesh, PartitionSpec("data", None)),
    )
    assert tree_shard_bytes({"m": x}) == x.nbytes // 8
    # replicated leaf: full bytes per device
    r = jax.device_put(
        jnp.zeros((8, 8), jnp.float32),
        NamedSharding(mesh, PartitionSpec(None, None)),
    )
    assert tree_shard_bytes({"m": x, "r": r}) == x.nbytes // 8 + r.nbytes


# ---------------------------------------------------------------------------
# the injected leak and the CI gate
# ---------------------------------------------------------------------------


def test_faultinject_leak_books_into_synthetic_watermark(tmp_path):
    """DDL_FAULT=leak@step: the held buffer shows up in leaked_bytes()
    and therefore in the synthetic watermark, and deactivate() releases
    it (the test API must not leak across tests)."""
    from ddl_tpu.obs import hbm
    from ddl_tpu.obs.events import EventWriter
    from ddl_tpu.utils import faultinject

    faultinject.activate("leak@step:2:1")  # 1 MB at step 2
    try:
        assert faultinject.leaked_bytes() == 0
        faultinject.check_step(1)
        assert faultinject.leaked_bytes() == 0
        faultinject.check_step(2)
        leaked = faultinject.leaked_bytes()
        assert leaked >= 1 << 20

        w = EventWriter(tmp_path, "leak", host=0)
        e = hbm.live_sample(w, params_bytes=100, opt_bytes=200)
        w.close()
        assert e["synthetic"] is True
        assert e["watermark"] == 300 + leaked
    finally:
        faultinject.deactivate()
    assert faultinject.leaked_bytes() == 0


def test_diff_fail_hbm_growth_gate(tmp_path, capsys):
    """The CI leak gate end-to-end: a leak-grown run against a clean
    baseline exits nonzero under --fail-hbm-growth; a matching clean
    run passes; a pre-ledger baseline is rejected loudly."""
    from ddl_tpu import cli

    def mk(job, extra_watermark):
        evs = [
            _ev(0, "run_start", 1.0),
            _ev(0, "hbm_sample", 2.0, params_bytes=600, opt_bytes=1200,
                watermark=1800, peak=1800, synthetic=True),
            _ev(0, "hbm_sample", 3.0, params_bytes=600, opt_bytes=1200,
                watermark=1800 + extra_watermark,
                peak=1800 + extra_watermark, synthetic=True),
            _ev(0, "run_end", 4.0),
        ]
        _append(tmp_path, job, 0, [json.dumps(e) for e in evs])

    mk("clean", 0)
    mk("clean2", 0)
    mk("leaky", 4000)  # > 2x growth: an injected leak's signature

    base = tmp_path / "base.json"
    cli.main(["obs", "baseline", "clean", "--log-dir", str(tmp_path),
              "--out", str(base)])
    capsys.readouterr()

    cli.main(["obs", "diff", "clean2", "--log-dir", str(tmp_path),
              "--baseline", str(base), "--fail-hbm-growth", "0.5"])
    out = capsys.readouterr().out
    assert "OK: peak HBM within the 50% growth gate" in out

    with pytest.raises(SystemExit, match="peak HBM.*above"):
        cli.main(["obs", "diff", "leaky", "--log-dir", str(tmp_path),
                  "--baseline", str(base), "--fail-hbm-growth", "0.5"])
    capsys.readouterr()

    # a baseline without an hbm account (pre-ledger) fails loudly
    stored = json.loads(base.read_text())
    del stored["summary"]["hbm"]
    old = tmp_path / "old.json"
    old.write_text(json.dumps(stored))
    with pytest.raises(SystemExit, match="regenerate the baseline"):
        cli.main(["obs", "diff", "clean2", "--log-dir", str(tmp_path),
                  "--baseline", str(old), "--fail-hbm-growth", "0.5"])


def test_leak_injected_training_run_trips_gate(tmp_path, capsys):
    """The whole loop: a real (tiny) training run with DDL_FAULT=leak
    emits hbm_samples whose synthetic watermark grows mid-run, and the
    gate catches it against the same trainer run without the fault."""
    from ddl_tpu import cli
    from ddl_tpu.obs import hbm
    from ddl_tpu.obs.events import EventWriter
    from ddl_tpu.utils import faultinject

    def run(job, fault):
        if fault:
            faultinject.activate(fault)
        try:
            w = EventWriter(tmp_path, job, host=0)
            for step in range(4):
                try:
                    faultinject.check_step(step)
                except Exception:
                    pass
                hbm.live_sample(
                    w, params_bytes=1000, opt_bytes=2000, step=step,
                )
            w.close()
        finally:
            faultinject.deactivate()

    run("noleak", None)
    run("leaks", "leak@step:2:2")  # 2 MB held from step 2 on

    base = tmp_path / "b.json"
    cli.main(["obs", "baseline", "noleak", "--log-dir", str(tmp_path),
              "--out", str(base)])
    capsys.readouterr()
    with pytest.raises(SystemExit, match="peak HBM"):
        cli.main(["obs", "diff", "leaks", "--log-dir", str(tmp_path),
                  "--baseline", str(base), "--fail-hbm-growth", "0.5"])
