"""Transformer LM family: TP / SP(ring) / EP(MoE) / FSDP strategy equivalence.

Same testing philosophy as the CNN path (tests/test_parallel.py): every
parallelised configuration must reproduce the single-device run of the same
model/seed — same loss, same post-Adam parameters — on the simulated
8-device CPU mesh.  The reference validates its strategies statistically
across cluster runs (ipynb/main.ipynb cell 5, SURVEY.md §4); here equivalence
is numeric and per-commit.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax

from ddl_tpu.models.transformer import LMConfig
from ddl_tpu.parallel.sharding import LMMeshSpec
from ddl_tpu.train.lm_steps import make_lm_step_fns


def tiny_cfg(**kw):
    base = dict(
        vocab_size=32,
        d_model=32,
        n_layers=2,
        n_heads=4,
        head_dim=8,
        d_ff=64,
        compute_dtype="float32",
        attn_impl="dense",
        remat=False,
    )
    base.update(kw)
    return LMConfig(**base)


def make_batch(rng, batch=4, seq=16, vocab=32):
    x = rng.integers(0, vocab, (batch, seq + 1))
    return jnp.asarray(x[:, :-1]), jnp.asarray(x[:, 1:])


def run_steps(cfg, spec, n_steps=2, batch=4, seq=16, **fns_kw):
    fns = make_lm_step_fns(
        cfg, spec, optax.adam(1e-3), jax.random.key(0), batch, seq, **fns_kw
    )
    rng = np.random.default_rng(0)
    state = fns.init_state()
    losses = []
    for _ in range(n_steps):
        inp, tgt = make_batch(rng, batch, seq, cfg.vocab_size)
        state, m = fns.train(state, inp, tgt)
        losses.append(float(m["loss"]))
    return state, losses


def flat_params(state):
    return {
        jax.tree_util.keystr(p): np.asarray(v)
        for p, v in jax.tree_util.tree_leaves_with_path(state.params)
    }


def assert_state_close(a, b, atol):
    fa, fb = flat_params(a), flat_params(b)
    assert fa.keys() == fb.keys()
    for k in fa:
        np.testing.assert_allclose(fa[k], fb[k], atol=atol, err_msg=k)


class TestStrategyEquivalence:
    def test_tp_sp_matches_single(self):
        """(data=2, seq=2, model=2) ring attention == single device."""
        ref, ref_losses = run_steps(tiny_cfg(), LMMeshSpec())
        par, par_losses = run_steps(
            tiny_cfg(attn_impl="ring", remat=True),
            LMMeshSpec(data=2, seq=2, model=2),
        )
        np.testing.assert_allclose(ref_losses, par_losses, atol=1e-4)
        assert_state_close(ref, par, atol=1e-4)

    def test_moe_ep_matches_single(self):
        """(data=2, model=2, expert=2) MoE == the same MoE on one device."""
        cfg = tiny_cfg(num_experts=4, expert_top_k=2)
        ref, ref_losses = run_steps(cfg, LMMeshSpec())
        par, par_losses = run_steps(cfg, LMMeshSpec(data=2, model=2, expert=2))
        np.testing.assert_allclose(ref_losses, par_losses, atol=1e-4)
        assert_state_close(ref, par, atol=1e-4)

    def test_moe_alltoall_matches_gspmd(self, monkeypatch):
        """The manual all_to_all EP dispatch (moe_ep='alltoall': per-shard
        sort dispatch + explicit lax.all_to_all in a partial-manual
        shard_map over 'expert') == the GSPMD dispatch on the same
        (data=2, expert=2) mesh — losses, post-Adam params, and router
        metrics.  The manual path's engagement is PINNED (a silent
        fallback to GSPMD would make this parity vacuous)."""
        import ddl_tpu.models.transformer as tf_mod

        calls = {"n": 0}
        real = tf_mod._ep_alltoall_moe

        def counting(*a, **kw):
            calls["n"] += 1
            return real(*a, **kw)

        monkeypatch.setattr(tf_mod, "_ep_alltoall_moe", counting)
        a2a, a2a_losses = run_steps(
            tiny_cfg(num_experts=4, expert_top_k=2, moe_ep="alltoall"),
            LMMeshSpec(data=2, expert=2),
        )
        assert calls["n"] > 0, "manual all_to_all path never engaged"
        ref, ref_losses = run_steps(
            tiny_cfg(num_experts=4, expert_top_k=2, moe_ep="gspmd"),
            LMMeshSpec(data=2, expert=2),
        )
        np.testing.assert_allclose(ref_losses, a2a_losses, atol=1e-4)
        assert_state_close(ref, a2a, atol=1e-4)

    def test_moe_ep_batch_not_replicated(self):
        """Batch shards over data AND expert: without it every non-MoE op
        would run ep-fold replicated on the expert shards."""
        from ddl_tpu.parallel.sharding import lm_logical_rules

        rules = dict(lm_logical_rules())
        assert rules["batch"] == ("data", "expert")
        assert rules["moe_batch"] == "data"  # dispatch tensors' token dim

        import pytest

        with pytest.raises(ValueError, match=r"data\*expert"):
            make_lm_step_fns(
                tiny_cfg(num_experts=4, expert_top_k=2),
                LMMeshSpec(data=2, expert=2), optax.adam(1e-3),
                jax.random.key(0), 2, 16,  # batch 2 < data*expert = 4
            )

    def test_fsdp_matches_unsharded(self):
        """FSDP param sharding changes placement, not math."""
        ref, ref_losses = run_steps(tiny_cfg(), LMMeshSpec(data=4, model=2))
        par, par_losses = run_steps(
            tiny_cfg(fsdp=True), LMMeshSpec(data=4, model=2)
        )
        np.testing.assert_allclose(ref_losses, par_losses, atol=1e-4)
        assert_state_close(ref, par, atol=1e-4)
        # and the params/optimizer state really are sharded over data
        kernel = par.params["block0"]["mlp"]["wi"]["kernel"]
        assert "data" in str(kernel.sharding.spec)

    def test_ring_equals_dense_attention(self):
        """Ring attention is numerically full attention (causal)."""
        ref, ref_losses = run_steps(tiny_cfg(), LMMeshSpec(data=2))
        par, par_losses = run_steps(
            tiny_cfg(attn_impl="ring"), LMMeshSpec(data=2, seq=4)
        )
        np.testing.assert_allclose(ref_losses, par_losses, atol=1e-4)

    def test_gqa_sharded_matches_single(self):
        """Grouped-query attention under TP: single device == (data=2,
        model=2), and the ring core (which sees broadcast K/V heads) ==
        dense — same GQA math everywhere."""
        cfg = tiny_cfg(n_kv_heads=2)
        ref, ref_losses = run_steps(cfg, LMMeshSpec())
        # K/V projections really are reduced: (d_model, Hkv*Dh)
        k_kernel = ref.params["block0"]["attn"]["k"]["kernel"]
        assert k_kernel.shape == (32, 2 * 8)
        par, par_losses = run_steps(cfg, LMMeshSpec(data=2, model=2))
        np.testing.assert_allclose(ref_losses, par_losses, atol=1e-4)
        assert_state_close(ref, par, atol=1e-4)
        ring, ring_losses = run_steps(
            tiny_cfg(n_kv_heads=2, attn_impl="ring"), LMMeshSpec(seq=2)
        )
        np.testing.assert_allclose(ref_losses, ring_losses, atol=1e-4)

    def test_gqa_tp_requires_whole_kv_heads(self):
        import pytest

        with pytest.raises(ValueError, match="n_kv_heads"):
            make_lm_step_fns(
                tiny_cfg(n_kv_heads=2), LMMeshSpec(model=4),
                optax.adam(1e-3), jax.random.key(0), 4, 16,
            )


class TestLearning:
    def test_remat_policy_invariance(self):
        """remat and its save policy change scheduling, never math: every
        setting must reproduce the no-remat run's losses and parameters."""
        import pytest

        from ddl_tpu.models.transformer import remat_block

        ref, ref_losses = run_steps(tiny_cfg(remat=False), LMMeshSpec())
        for policy in ("full", "dots", "dots_no_batch"):
            state, losses = run_steps(
                tiny_cfg(remat=True, remat_policy=policy), LMMeshSpec()
            )
            np.testing.assert_allclose(losses, ref_losses, atol=1e-6)
            assert_state_close(state, ref, atol=1e-6)
        with pytest.raises(ValueError, match="remat_policy"):
            remat_block(tiny_cfg(remat=True, remat_policy="typo"))

    def test_lm_memorizes_periodic_sequences(self):
        """Next-token loss collapses on x[t+1] = x[t] + 1 (mod V) data."""
        cfg = tiny_cfg()
        fns = make_lm_step_fns(
            cfg, LMMeshSpec(data=2, model=2), optax.adam(3e-3),
            jax.random.key(0), 8, 16,
        )
        rng = np.random.default_rng(0)
        state = fns.init_state()
        first = last = None
        for i in range(60):
            phase = rng.integers(0, 32, (8, 1))
            seq = (phase + np.arange(17)) % 32
            inp, tgt = jnp.asarray(seq[:, :-1]), jnp.asarray(seq[:, 1:])
            state, m = fns.train(state, inp, tgt)
            if i == 0:
                first = float(m["loss"])
            last = float(m["loss"])
        assert last < first * 0.2, (first, last)

    def test_moe_trains_and_balances(self):
        cfg = tiny_cfg(num_experts=4, expert_top_k=2, moe_aux_weight=0.02)
        fns = make_lm_step_fns(
            cfg, LMMeshSpec(data=2, expert=2, model=2), optax.adam(3e-3),
            jax.random.key(0), 8, 16,
        )
        rng = np.random.default_rng(0)
        state = fns.init_state()
        losses, auxes = [], []
        for _ in range(30):
            phase = rng.integers(0, 32, (8, 1))
            seq = (phase + np.arange(17)) % 32
            state, m = fns.train(state, jnp.asarray(seq[:, :-1]), jnp.asarray(seq[:, 1:]))
            losses.append(float(m["ce"]))
            auxes.append(float(m["moe_aux"]))
        assert losses[-1] < losses[0] * 0.5, (losses[0], losses[-1])
        # aux loss is E * sum f_e p_e; perfectly balanced top-k routing gives
        # ~1.0 — it must stay finite and in a sane band
        assert 0.5 < auxes[-1] < 4.0, auxes[-1]


class TestRouting:
    def test_dispatch_respects_capacity(self):
        from ddl_tpu.models.transformer import _top_k_dispatch

        rng = np.random.default_rng(1)
        gates = jax.nn.softmax(jnp.asarray(rng.normal(size=(2, 12, 4))), -1)
        dispatch, combine = _top_k_dispatch(gates, k=2, capacity=3)
        # no expert slot is used twice within a group
        slot_use = np.asarray(dispatch.sum(axis=1))  # (B, E, C)
        assert slot_use.max() <= 1.0 + 1e-6
        # each token goes to at most k slots
        tok_use = np.asarray(dispatch.sum(axis=(2, 3)))
        assert tok_use.max() <= 2 + 1e-6
        # combine weights of a routed token sum to ~1 (renormalised top-k)
        routed = tok_use >= 2 - 1e-6
        csum = np.asarray(combine.sum(axis=(2, 3)))
        np.testing.assert_allclose(csum[routed], 1.0, atol=1e-5)

    def test_bf16_compute_path_finite(self):
        cfg = tiny_cfg(compute_dtype="bfloat16", num_experts=2, expert_top_k=1)
        _, losses = run_steps(cfg, LMMeshSpec(data=2, model=2, expert=2), n_steps=1)
        assert np.isfinite(losses).all()

    def test_sort_dispatch_matches_einsum(self):
        """The sort/scatter/gather dispatch reproduces the one-hot einsum
        path bit-for-bit in routing decisions: same output, same aux loss,
        same router metrics — including under capacity starvation, where
        the slot-priority order (choice rank, then position) decides
        exactly which token-choices drop."""
        import dataclasses

        from ddl_tpu.models.transformer import MoeMlp

        for cf in (1.5, 0.5):  # ample and starved capacity
            cfg_s = tiny_cfg(
                num_experts=4, expert_top_k=2, capacity_factor=cf,
                moe_dispatch="sort",
            )
            cfg_e = dataclasses.replace(cfg_s, moe_dispatch="einsum")
            x = jax.random.normal(jax.random.key(2), (2, 16, 32))
            params = MoeMlp(cfg_s).init(jax.random.key(0), x)
            outs = {}
            for name, cfg in (("sort", cfg_s), ("einsum", cfg_e)):
                (y, aux), inter = MoeMlp(cfg).apply(
                    params, x, mutable=["intermediates"]
                )
                outs[name] = (y, aux, inter["intermediates"])
            y_s, aux_s, i_s = outs["sort"]
            y_e, aux_e, i_e = outs["einsum"]
            np.testing.assert_allclose(y_s, y_e, atol=1e-5, err_msg=f"cf={cf}")
            np.testing.assert_allclose(aux_s, aux_e, atol=1e-6)
            np.testing.assert_allclose(
                i_s["moe_drop_frac"], i_e["moe_drop_frac"], atol=1e-6
            )
            np.testing.assert_allclose(
                i_s["moe_expert_load"], i_e["moe_expert_load"], atol=1e-6
            )
            # the permutation gathers use hand-written VJPs (backward is
            # gathers, not scatter-adds); they must match the einsum
            # path's autodiff gradients, not just its forward
            def loss(params, x, cfg=None):
                (y, aux), _ = MoeMlp(cfg).apply(
                    params, x, mutable=["intermediates"]
                )
                return (y ** 2).sum() + aux

            g_s = jax.grad(loss, argnums=(0, 1))(params, x, cfg=cfg_s)
            g_e = jax.grad(loss, argnums=(0, 1))(params, x, cfg=cfg_e)
            for a, b in zip(jax.tree.leaves(g_s), jax.tree.leaves(g_e)):
                np.testing.assert_allclose(a, b, atol=1e-4)

    def test_routing_plan_rejects_degenerate_groups(self):
        """Prime/near-prime sequence lengths must not collapse to 1-2
        token routing groups — the plan falls back to whole-sequence."""
        import dataclasses

        from ddl_tpu.models.transformer import moe_routing_plan

        cfg = tiny_cfg(num_experts=4, moe_group=256)
        assert moe_routing_plan(cfg, 1024) == ("einsum", 256)
        assert moe_routing_plan(cfg, 514) == ("einsum", 514)  # 2*257
        assert moe_routing_plan(cfg, 509) == ("einsum", 509)  # prime
        assert moe_routing_plan(cfg, 192) == ("einsum", 192)
        big = dataclasses.replace(cfg, moe_group=0)
        assert moe_routing_plan(big, 4096) == ("sort", 4096)
        assert moe_routing_plan(
            dataclasses.replace(cfg, moe_dispatch="sort"), 1024
        ) == ("sort", 256)

    def test_routing_groups_match_whole_sequence_when_capacity_ample(self):
        """Splitting the sequence into routing groups only changes WHICH
        tokens drop under pressure; with ample capacity nothing drops in
        either layout, so grouped == ungrouped exactly."""
        import dataclasses

        from ddl_tpu.models.transformer import MoeMlp

        cfg_g = tiny_cfg(
            num_experts=4, expert_top_k=2, capacity_factor=8.0, moe_group=4
        )
        cfg_w = dataclasses.replace(cfg_g, moe_group=0)
        x = jax.random.normal(jax.random.key(3), (2, 16, 32))
        params = MoeMlp(cfg_g).init(jax.random.key(0), x)
        outs = {}
        for name, cfg in (("grouped", cfg_g), ("whole", cfg_w)):
            (y, aux), inter = MoeMlp(cfg).apply(
                params, x, mutable=["intermediates"]
            )
            outs[name] = (y, inter["intermediates"]["moe_drop_frac"])
        assert float(outs["grouped"][1][0]) == 0.0  # genuinely drop-free
        np.testing.assert_allclose(
            outs["grouped"][0], outs["whole"][0], atol=1e-6
        )

    def test_sort_dispatch_ep_matches_single(self):
        """Sort dispatch under real expert parallelism == single device."""
        cfg = tiny_cfg(num_experts=4, expert_top_k=2, capacity_factor=0.75,
                       moe_dispatch="sort")
        ref, ref_losses = run_steps(cfg, LMMeshSpec())
        par, par_losses = run_steps(cfg, LMMeshSpec(data=2, model=2, expert=2))
        np.testing.assert_allclose(ref_losses, par_losses, atol=1e-4)
        assert_state_close(ref, par, atol=1e-4)


def test_gqa_ulysses_matches_single():
    """GQA + Ulysses SP: the broadcast K/V heads ride the all-to-all like
    full heads; sharded == single device."""
    cfg = tiny_cfg(n_kv_heads=2, attn_impl="ulysses")
    ref, ref_losses = run_steps(tiny_cfg(n_kv_heads=2), LMMeshSpec())
    par, par_losses = run_steps(cfg, LMMeshSpec(data=2, seq=2))
    np.testing.assert_allclose(ref_losses, par_losses, atol=1e-4)
    assert_state_close(ref, par, atol=1e-4)


def test_ce_chunk_matches_dense_loss():
    """ce_chunk reproduces the dense-CE training trajectory exactly —
    flat path, TP (vocab-sharded chunks), and both pipeline schedules."""
    import dataclasses

    ref, ref_losses = run_steps(tiny_cfg(), LMMeshSpec())
    for spec, kw in (
        (LMMeshSpec(), {}),
        (LMMeshSpec(data=2, model=2), {}),
        (LMMeshSpec(data=2, pipe=2), {"n_steps": 2}),
        (LMMeshSpec(data=2, pipe=2),
         {"n_steps": 2, "pipeline_schedule": "1f1b"}),
    ):
        chunked, losses = run_steps(
            tiny_cfg(ce_chunk=4), spec, **kw
        )
        np.testing.assert_allclose(
            ref_losses[: len(losses)], losses, atol=2e-4,
            err_msg=f"{spec} {kw}",
        )


def test_ce_chunk_rejects_seq_sharding():
    import pytest

    with pytest.raises(ValueError, match="ce_chunk"):
        make_lm_step_fns(
            tiny_cfg(ce_chunk=4, attn_impl="ring"), LMMeshSpec(seq=2),
            optax.adam(1e-3), jax.random.key(0), 4, 16,
        )


def test_moe_router_metrics_surface_drops_and_load():
    """MoE runs report router token-drop fraction and expert-load spread
    (VERDICT round 2: capacity overflow used to drop tokens invisibly)."""
    import optax

    def step_metrics(capacity_factor, remat=False):
        cfg = tiny_cfg(
            num_experts=4, capacity_factor=capacity_factor, remat=remat
        )
        fns = make_lm_step_fns(
            cfg, LMMeshSpec(), optax.adam(1e-3), jax.random.key(0), 4, 16
        )
        rng = np.random.default_rng(0)
        inp, tgt = make_batch(rng)
        state, m = fns.train(fns.init_state(), inp, tgt)
        em = fns.evaluate(state, inp, tgt)
        return m, em

    m, em = step_metrics(1.5)
    for d in (m, em):
        assert 0.0 <= float(d["moe_drop_frac"]) < 1.0
        assert float(d["moe_load_max"]) >= float(d["moe_load_min"]) >= 0.0
    # starved capacity must make the drop visible
    m_starved, _ = step_metrics(0.25)
    assert float(m_starved["moe_drop_frac"]) > 0.2
    assert float(m_starved["moe_drop_frac"]) > float(m["moe_drop_frac"])
    # sown stats survive the remat'd block too
    m_remat, _ = step_metrics(1.5, remat=True)
    assert 0.0 <= float(m_remat["moe_drop_frac"]) < 1.0
    # dense runs stay free of router keys
    fns = make_lm_step_fns(
        tiny_cfg(), LMMeshSpec(), optax.adam(1e-3), jax.random.key(0), 4, 16
    )
    rng = np.random.default_rng(0)
    inp, tgt = make_batch(rng)
    _, m_dense = fns.train(fns.init_state(), inp, tgt)
    assert "moe_drop_frac" not in m_dense


def test_ce_vocab_chunk_matches_dense_loss():
    """ce_vocab_chunk (vocab-streamed loss edge, custom VJP) reproduces
    the dense-CE training trajectory — flat path and with data + seq
    sharding (the scan slices W; hidden stays T-sharded)."""
    ref, ref_losses = run_steps(tiny_cfg(), LMMeshSpec())
    for spec, kw in (
        (LMMeshSpec(), {}),
        (LMMeshSpec(data=2, seq=2), {}),
        (LMMeshSpec(data=2, pipe=2), {"n_steps": 2}),  # GPipe head path
    ):
        chunked, losses = run_steps(
            tiny_cfg(ce_vocab_chunk=8), spec, **kw
        )
        np.testing.assert_allclose(
            ref_losses[: len(losses)], losses, atol=2e-4,
            err_msg=f"{spec} {kw}",
        )


def test_ce_vocab_chunk_validation():
    import dataclasses

    import pytest

    with pytest.raises(ValueError, match="mutually exclusive"):
        tiny_cfg(ce_chunk=4, ce_vocab_chunk=8)
    with pytest.raises(ValueError, match="ce_vocab_chunk"):
        make_lm_step_fns(
            tiny_cfg(ce_vocab_chunk=8), LMMeshSpec(model=2),
            optax.adam(1e-3), jax.random.key(0), 4, 16,
        )
    with pytest.raises(ValueError, match="1F1B"):
        make_lm_step_fns(
            tiny_cfg(ce_vocab_chunk=8), LMMeshSpec(data=2, pipe=2),
            optax.adam(1e-3), jax.random.key(0), 4, 16,
            num_microbatches=2, pipeline_schedule="1f1b",
        )
