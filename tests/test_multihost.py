"""True multi-process integration tests of the distributed stack.

The reference cannot test its distributed paths without a live NCCL
cluster (SURVEY.md §4 — "nothing mocks NCCL").  Here two ACTUAL processes
form a world over Gloo on CPU (4 simulated devices each -> one 8-device
global mesh) and run end-to-end: the full DP CNN Trainer (launcher env
bootstrap, cross-process global-batch assembly, metric allgathers) and
the LM family on a multi-host (data, pipe, model) FSDP mesh under the
1F1B schedule, in two device-placement phases so the data-axis
collectives AND the pipe-axis stage-handoff ppermutes each cross the
process boundary (multihost_worker.main_lm).  Both workers must finish
and agree bit-for-bit on the global value of every parameter.
"""

import os
import socket
import subprocess
import sys
from pathlib import Path

import pytest


WORKER = Path(__file__).parent / "multihost_worker.py"


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("localhost", 0))
        return s.getsockname()[1]


@pytest.mark.parametrize("mode", ["cnn", "lm"])
def test_two_process_world(mode, tmp_path):
    port = _free_port()
    env_base = {
        k: v for k, v in os.environ.items()
        if k not in ("XLA_FLAGS", "JAX_PLATFORMS")
    }
    procs, logs = [], []
    for pid in (0, 1):
        env = dict(
            env_base,
            DDL_COORDINATOR=f"localhost:{port}",
            DDL_NUM_PROCESSES="2",
            DDL_PROCESS_ID=str(pid),
            DDL_TEST_LOG_DIR=str(tmp_path / "logs"),
            DDL_TEST_MODE=mode,
        )
        # output to files, not pipes: a worker filling an undrained pipe
        # would block mid-collective and stall the whole world
        log = open(tmp_path / f"worker{pid}.log", "w+")
        logs.append(log)
        procs.append(subprocess.Popen(
            [sys.executable, str(WORKER)],
            env=env,
            stdout=log,
            stderr=subprocess.STDOUT,
            text=True,
        ))
    outs = []
    for p, log in zip(procs, logs):
        try:
            p.wait(timeout=540)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            raise
        log.seek(0)
        outs.append(log.read())
        log.close()
    for pid, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"worker {pid} failed:\n{out[-4000:]}"
        assert "WORKER_OK" in out, out[-2000:]
    # both processes trained the same global model
    sums = sorted(
        line.split("checksum=")[1]
        for out in outs
        for line in out.splitlines()
        if "WORKER_OK" in line
    )
    assert len(sums) == 2 and sums[0] == sums[1], sums
