"""Partition-rule engine (parallel/rules.py): matching semantics
(first-match-wins, search-anywhere, anchoring, scalar fallthrough,
unmatched-leaf error), per-family tables resolving every real parameter
path identically to the legacy logical-axis resolution, the ZeRO shard
derivation, the optimizer-HBM accounting, and the rule-driven
shard/gather pair.
"""

import jax
import jax.numpy as jnp
import jax.tree_util as jtu
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from ddl_tpu.parallel import rules as R

# ---------------------------------------------------------------------------
# matching semantics
# ---------------------------------------------------------------------------


def _leaf(shape):
    return jax.ShapeDtypeStruct(tuple(shape), jnp.float32)


def test_first_match_wins_precedence():
    rules = (
        (r"attn/q/kernel$", P(None, "model")),
        (r"kernel$", P("model", None)),  # broader rule later
    )
    tree = {"attn": {"q": {"kernel": _leaf((8, 8))},
                     "out": {"kernel": _leaf((8, 8))}}}
    specs = R.match_partition_rules(rules, tree)
    assert specs["attn"]["q"]["kernel"] == P(None, "model")
    assert specs["attn"]["out"]["kernel"] == P("model", None)
    # reversed order: the broad rule shadows the specific one
    specs2 = R.match_partition_rules(tuple(reversed(rules)), tree)
    assert specs2["attn"]["q"]["kernel"] == P("model", None)


def test_search_matches_anywhere_and_anchor_pins_end():
    rules = ((r"mlp/wi/kernel$", P(None, "model")),)
    # the pattern matches mid-path (optimizer moments embed param paths)
    tree = {"0": {"mu": {"block0": {"mlp": {"wi": {"kernel": _leaf((8, 32))}}}}}}
    specs = R.match_partition_rules(rules, tree)
    assert jtu.tree_leaves(specs, is_leaf=lambda x: isinstance(x, P))[0] == P(None, "model")
    # the $ anchor refuses a path that merely CONTAINS the name
    with pytest.raises(R.UnmatchedLeafError):
        R.match_partition_rules(
            rules, {"mlp": {"wi": {"kernel_scale": _leaf((8, 32))}}}
        )


def test_scalars_and_single_elements_replicate_without_rules():
    specs = R.match_partition_rules(
        (), {"count": _leaf(()), "one": _leaf((1,))}
    )
    assert specs == {"count": P(), "one": P()}


def test_unmatched_leaf_error_names_paths_and_strict_false_replicates():
    tree = {"mystery": {"kernel": _leaf((16, 16))}}
    with pytest.raises(R.UnmatchedLeafError) as ei:
        R.match_partition_rules((), tree, strict=True)
    assert "mystery/kernel" in str(ei.value)
    assert R.match_partition_rules((), tree, strict=False) == {
        "mystery": {"kernel": P()}
    }


def test_provenance_distinguishes_explicit_replication():
    rules = ((r"pos_embed$", P()), (r"kernel$", P(None, "model")))
    tree = {"pos_embed": _leaf((1, 4, 64)), "q": {"kernel": _leaf((8, 8))}}
    prov = {name: (spec, pat)
            for name, _l, spec, pat in R.match_with_provenance(rules, tree)}
    assert prov["pos_embed"] == (P(), r"pos_embed$")
    assert prov["q/kernel"] == (P(None, "model"), r"kernel$")


# ---------------------------------------------------------------------------
# family tables vs the legacy logical-axis resolution
# ---------------------------------------------------------------------------


def _lm_mesh():
    from ddl_tpu.parallel.sharding import LMMeshSpec, build_lm_mesh

    return build_lm_mesh(LMMeshSpec(data=2, model=2, expert=2))


def _assert_table_matches_logical(abs_params, table, fsdp, mesh):
    import flax.linen as nn

    from ddl_tpu.parallel.sharding import lm_logical_rules

    logical = nn.get_partition_spec(abs_params)
    legacy = nn.logical_to_mesh_sharding(logical, mesh, lm_logical_rules(fsdp))
    unboxed = nn.meta.unbox(abs_params)
    ours = table.shardings(unboxed, mesh)
    for (path, leaf), (_, l), (_, o) in zip(
        jtu.tree_leaves_with_path(unboxed),
        jtu.tree_leaves_with_path(legacy),
        jtu.tree_leaves_with_path(ours),
    ):
        assert l.is_equivalent_to(o, len(leaf.shape)), (
            f"{R.tree_path_str(path)}: legacy {l.spec} != table {o.spec}"
        )


@pytest.mark.parametrize("fsdp", [False, True])
@pytest.mark.parametrize("moe", [0, 2])
def test_lm_table_matches_logical_resolution(fsdp, moe):
    from ddl_tpu.models.transformer import LMConfig, TransformerLM

    cfg = LMConfig(
        vocab_size=512, d_model=64, n_layers=2, n_heads=4, head_dim=16,
        d_ff=256, compute_dtype="float32", num_experts=moe, fsdp=fsdp,
    )
    abs_params = jax.eval_shape(
        lambda r: TransformerLM(cfg, None).init(
            r, jnp.zeros((4, 8), jnp.int32)
        )["params"],
        jax.random.key(0),
    )
    _assert_table_matches_logical(abs_params, R.lm_rules(fsdp), fsdp, _lm_mesh())


@pytest.mark.parametrize("fsdp", [False, True])
def test_vit_table_matches_logical_resolution(fsdp):
    from ddl_tpu.models.vit import ViT, ViTConfig

    cfg = ViTConfig(
        image_size=16, patch_size=8, d_model=64, n_layers=2, n_heads=4,
        head_dim=16, d_ff=256, compute_dtype="float32", remat=False,
        fsdp=fsdp,
    )
    abs_params = jax.eval_shape(
        lambda r: ViT(cfg).init(
            r, jnp.zeros((2, 16, 16, 3), jnp.float32)
        )["params"],
        jax.random.key(0),
    )
    _assert_table_matches_logical(abs_params, R.vit_rules(fsdp), fsdp, _lm_mesh())


def test_gqa_lm_paths_resolve():
    """Grouped-query configs change K/V shapes, not names — the table
    must still cover every leaf."""
    from ddl_tpu.models.transformer import LMConfig, TransformerLM

    import flax.linen as nn

    cfg = LMConfig(
        vocab_size=128, d_model=64, n_layers=1, n_heads=4, head_dim=16,
        d_ff=128, compute_dtype="float32", n_kv_heads=2,
    )
    abs_params = nn.meta.unbox(jax.eval_shape(
        lambda r: TransformerLM(cfg, None).init(
            r, jnp.zeros((2, 8), jnp.int32)
        )["params"],
        jax.random.key(0),
    ))
    R.lm_rules().specs(abs_params)  # strict: raises on any gap


def test_cnn_table_covers_densenet_and_decode_table_is_lm():
    from ddl_tpu.config import ModelConfig
    from ddl_tpu.models import build_stages
    from ddl_tpu.models.densenet import init_stages

    cfg = ModelConfig(
        growth_rate=4, block_config=(2, 2), num_init_features=8, bn_size=2,
        num_classes=5, split_blocks=(1,), compute_dtype="float32",
        remat=False,
    )
    stages = build_stages(cfg, num_stages=1)
    params = jax.eval_shape(
        lambda r: init_stages(stages, r, 16)[0], jax.random.key(0)
    )
    specs = R.cnn_rules().specs(params)
    assert all(
        s == P() for s in jtu.tree_leaves(specs, is_leaf=lambda x: isinstance(x, P))
    )
    assert R.cnn_rules().contract()["replicated_params_ok"] is True
    d = R.decode_rules()
    assert d.rules == R.lm_rules().rules
    assert d.contract()["donate_state"] is False
    assert d.in_specs["prompt"] == R.DECODE_TOKEN_SPEC


# ---------------------------------------------------------------------------
# ZeRO derivation + HBM accounting
# ---------------------------------------------------------------------------


def test_zero_shard_spec_rules():
    mesh = _lm_mesh()  # data=2, model=2, expert=2
    # first unsharded divisible dim gets 'data'
    assert R.zero_shard_spec(P(None, "model"), (64, 256), mesh) == P("data", "model")
    # dim 0 taken by 'model': falls through to dim 1
    assert R.zero_shard_spec(P("model", None), (512, 64), mesh) == P("model", "data")
    # under threshold: stays replicated
    assert R.zero_shard_spec(P(), (100,), mesh) is None
    assert R.zero_shard_spec(P(), (16384,), mesh) == P("data")
    # FSDP leaves already use 'data' — no double shard
    assert R.zero_shard_spec(P("data", "model"), (64, 256), mesh) is None
    # no divisible dim: stays replicated (prime-ish dims)
    assert R.zero_shard_spec(P(), (3, 8191), mesh, threshold=1) is None
    # trivial axis: no-op
    from ddl_tpu.parallel.sharding import LMMeshSpec, build_lm_mesh

    mesh1 = build_lm_mesh(LMMeshSpec(data=1, model=2))
    assert R.zero_shard_spec(P(None, "model"), (64, 256), mesh1) is None
    # threshold override honored
    assert R.zero_shard_spec(P(), (128,), mesh, threshold=64) == P("data")


def test_optimizer_hbm_bytes_accounting():
    mesh = _lm_mesh()
    table = R.RuleTable(
        family="t",
        rules=(("big$", P(None, "model")), ("small$", P())),
        in_specs={},
    )
    params = {"big": _leaf((64, 256)), "small": _leaf((10, 10))}
    est = R.optimizer_hbm_bytes(table, params, mesh)
    # big: 16384 elems * 8 B (mu+nu) over model=2 -> 65536 B/dev
    # small: 100 elems * 8 B replicated -> 800
    assert est["replicated_bytes"] == 64 * 256 * 8 // 2 + 100 * 8
    # zero: big additionally over data=2
    assert est["zero_bytes"] == 64 * 256 * 8 // 4 + 100 * 8
    assert est["zero_sharded_leaves"] == 1 and est["leaves"] == 2
    assert est["dp"] == 2


def test_shard_and_gather_round_trip():
    import numpy as np

    mesh = _lm_mesh()
    specs = {"w": P("data", "model"), "b": P()}
    shard, gather = R.make_shard_and_gather_fns(mesh, specs)
    tree = {"w": jnp.arange(64.0).reshape(8, 8), "b": jnp.ones((3,))}
    sharded = shard(tree)
    assert sharded["w"].sharding == NamedSharding(mesh, P("data", "model"))
    back = gather(sharded)
    assert isinstance(back["w"], np.ndarray)
    np.testing.assert_array_equal(back["w"], np.asarray(tree["w"]))
    np.testing.assert_array_equal(back["b"], np.asarray(tree["b"]))


def test_state_rule_shardings_cover_moments():
    """checkpoint.state_rule_shardings: moments inherit the parameter
    placement via path-embedding; step/count fall through replicated."""
    import optax

    from ddl_tpu import checkpoint as ckpt

    mesh = _lm_mesh()
    table = R.RuleTable(
        family="t", rules=(("wi/kernel$", P(None, "model")),), in_specs={},
    )
    params = {"wi": {"kernel": jnp.zeros((8, 64))}}
    tx = optax.adam(1e-3)
    state = {"step": jnp.zeros((), jnp.int32), "params": params,
             "opt_state": tx.init(params)}
    sh = ckpt.state_rule_shardings(state, table, mesh)
    assert sh["params"]["wi"]["kernel"].spec == P(None, "model")
    assert sh["opt_state"][0].mu["wi"]["kernel"].spec == P(None, "model")
    assert sh["opt_state"][0].nu["wi"]["kernel"].spec == P(None, "model")
    assert sh["step"].spec == P()
    assert sh["opt_state"][0].count.spec == P()
