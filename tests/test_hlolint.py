"""Compiled-IR lint (`ddl_tpu lint --hlo`, ddl_tpu/analysis/hlolint.py):
text parsers over synthetic HLO/StableHLO fixtures
(tests/lint_fixtures/hlo/), the IR rule family against known-good /
known-bad programs, two-shape fingerprint diffing, and the
HLO_BASELINE.json drift-gate semantics (fail on growth, stale on
shrink) — all without compiling a single program, so the whole module
runs in milliseconds.  The live end-to-end gate (lower + compile every
probe and diff against the committed baseline) is the slow-marked test
in test_analysis.py.
"""

import json
from pathlib import Path

import pytest

from ddl_tpu.analysis.findings import Finding
from ddl_tpu.analysis.hlolint import (
    HLO_PROBES,
    ProgramSpec,
    affected_probes,
    apply_rules,
    build_inventory,
    diff_baseline,
    findings_for,
    group_axes,
    load_hlo_baseline,
    parse_aliases,
    parse_hlo_ops,
    parse_param_bytes,
    parse_replica_groups,
    parse_stablehlo_ops,
    probe_names,
    save_hlo_baseline,
    shape_bytes,
    structural_fingerprint,
)

FIXTURES = Path(__file__).parent / "lint_fixtures" / "hlo"

# fixture programs are written against this probe mesh: device id =
# data * 2 + model (row-major), 8 devices
MESH = [("data", 4), ("model", 2)]

ZERO_PLAN = {
    "axis": "data",
    "threshold": 8192,
    "eligible": [
        {
            "name": "mlp/wi/kernel", "size": 16384,
            "shape": [64, 256], "gather_shape": [64, 128],
        },
    ],
    "gather_shapes": [[64, 128]],
    "leaf_shard_shapes": [[64, 128]],
}


class _FakeCompiled:
    def __init__(self, text):
        self._text = text

    def as_text(self):
        return self._text


class _FakeLowered:
    """Duck-types a jax .lower() result for build_inventory: StableHLO
    text via as_text(), compiled HLO via compile().as_text() — or a
    compile() that raises, like the pipeline programs on XLA:CPU."""

    def __init__(self, shlo, hlo=None):
        self._shlo = shlo
        self._hlo = hlo

    def as_text(self):
        return self._shlo

    def compile(self):
        if self._hlo is None:
            raise RuntimeError("UNIMPLEMENTED: PartitionId (fixture)")
        return _FakeCompiled(self._hlo)


def _fixture(name):
    return (FIXTURES / name).read_text()


def _spec(name, hlo=None, shlo="", **kw):
    kw.setdefault("mesh_axes", MESH)
    return ProgramSpec(
        name=name, lowered=_FakeLowered(shlo, hlo),
        path="ddl_tpu/train/steps.py", line=48, **kw,
    )


# ---------------------------------------------------------------------------
# text parsers
# ---------------------------------------------------------------------------


def test_parse_replica_groups_explicit():
    assert parse_replica_groups("{{0,2,4,6},{1,3,5,7}}") == [
        [0, 2, 4, 6], [1, 3, 5, 7],
    ]
    assert parse_replica_groups("{{0}, {1}}") == [[0], [1]]


def test_parse_replica_groups_iota():
    assert parse_replica_groups("[2,4]<=[8]") == [
        [0, 1, 2, 3], [4, 5, 6, 7],
    ]
    # transposed iota: arange(8).reshape(4,2).T.reshape(2,4)
    assert parse_replica_groups("[2,4]<=[4,2]T(1,0)") == [
        [0, 2, 4, 6], [1, 3, 5, 7],
    ]


def test_group_axes_labels():
    assert group_axes([[0, 2, 4, 6], [1, 3, 5, 7]], MESH) == "data"
    assert group_axes([[0, 1], [2, 3]], MESH) == "model"
    assert group_axes([[0, 1, 2, 3], [4, 5, 6, 7]], MESH) == "data+model"
    assert group_axes([[0], [1]], MESH) == "none"
    assert group_axes([[0, 1]], []) == "devices"


def test_shape_bytes_scalar_and_tuple():
    assert shape_bytes("f32[64,128]{1,0}") == 64 * 128 * 4
    assert shape_bytes("bf16[8]{0}") == 16
    assert shape_bytes("(f32[8]{0}, u32[2]{0})") == 32 + 8
    assert shape_bytes("pred[]") == 1


def test_parse_hlo_ops_census():
    ops = parse_hlo_ops(_fixture("zero_good.hlo.txt"))
    kinds = sorted(o.kind for o in ops)
    assert kinds == ["all-gather", "all-gather", "all-reduce", "copy"]
    big = next(o for o in ops if o.dims == (64, 128) and
               o.kind == "all-gather")
    assert group_axes(big.groups, MESH) == "data"
    assert big.op_name == "jit(train_step)/jit(main)/add"
    assert big.bytes == 64 * 128 * 4


def test_parse_aliases_and_param_bytes():
    text = _fixture("aliased.hlo.txt")
    aliases = parse_aliases(text)
    assert ("0", 0, "") in aliases
    assert ("1", 1, "") in aliases
    # nested tuple index entries carry their param index path
    assert any(pidx != "" for _o, _p, pidx in aliases)
    pb = parse_param_bytes(text)
    assert pb[0] == 64 * 128 * 4
    assert pb[1] == 256 * 4


def test_parse_stablehlo_ops_permutes():
    counts, permutes = parse_stablehlo_ops(_fixture("pipeline_good.shlo.txt"))
    assert counts["collective-permute"] == 2
    assert counts["all-reduce"] == 1
    assert [p["pairs"] for p in permutes] == [
        [[0, 2], [1, 3], [4, 6], [5, 7]],
        [[2, 0], [3, 1], [6, 4], [7, 5]],
    ]
    assert permutes[0]["bytes"] == 4 * 32 * 64 * 4


def test_structural_fingerprint_ignores_constant_motion():
    a = 'x = "stablehlo.constant" y = "stablehlo.add" z = "stablehlo.dot"'
    b = 'x = "stablehlo.add" y = "stablehlo.constant" z = "stablehlo.dot"'
    c = 'x = "stablehlo.add" y = "stablehlo.dot" z = "stablehlo.dot"'
    assert structural_fingerprint(a) == structural_fingerprint(b)
    assert structural_fingerprint(a) != structural_fingerprint(c)


# ---------------------------------------------------------------------------
# rule family over fixture programs
# ---------------------------------------------------------------------------


def test_zero_rules_clean_on_good_fixture():
    inv = build_inventory(_spec(
        "cnn_dp_zero", hlo=_fixture("zero_good.hlo.txt"),
        zero_plan=ZERO_PLAN,
    ))
    assert apply_rules(inv) == []


def test_oversized_all_gather_flagged():
    inv = build_inventory(_spec(
        "cnn_dp_zero", hlo=_fixture("zero_bad_gather.hlo.txt"),
        zero_plan=ZERO_PLAN,
    ))
    fs = apply_rules(inv)
    assert [f.rule for f in fs] == ["oversized-all-gather"]
    assert "f32[512,64]" in fs[0].message
    # probe-attributed: file:line of the step factory, program-prefixed
    assert fs[0].path == "ddl_tpu/train/steps.py"
    assert fs[0].message.startswith("cnn_dp_zero: ")


def test_zero_missing_reduce_scatter_flagged():
    inv = build_inventory(_spec(
        "cnn_dp_zero", hlo=_fixture("zero_bad_missing.hlo.txt"),
        zero_plan=ZERO_PLAN,
    ))
    fs = apply_rules(inv)
    assert [f.rule for f in fs] == ["zero-missing-reduce-scatter"]
    assert "mlp/wi/kernel" in fs[0].message


def test_reduce_scatter_satisfies_the_cycle():
    text = _fixture("zero_bad_missing.hlo.txt").replace(
        "all-reduce.1 = f32[64,128]{1,0} all-reduce(",
        "reduce-scatter.1 = f32[64,128]{1,0} reduce-scatter(",
    )
    inv = build_inventory(_spec("cnn_dp_zero", hlo=text,
                                zero_plan=ZERO_PLAN))
    assert apply_rules(inv) == []


def test_pipeline_symmetry_clean_on_good_fixture():
    inv = build_inventory(_spec(
        "lm_pipeline", shlo=_fixture("pipeline_good.shlo.txt"),
        pipeline=True,
    ))
    assert inv.data["level"] == "stablehlo"  # compile() raised
    assert inv.notes  # the fallback is explained, not silent
    assert apply_rules(inv) == []


def test_pipeline_symmetry_flags_asymmetric_rings():
    inv = build_inventory(_spec(
        "lm_pipeline", shlo=_fixture("pipeline_bad_asym.shlo.txt"),
        pipeline=True,
    ))
    rules = [f.rule for f in apply_rules(inv)]
    assert rules and set(rules) == {"pipeline-collective-symmetry"}
    # both failure modes: duplicated target (non-bijection) AND a
    # forward ring with no inverse partner
    assert len(rules) >= 2


def test_pipeline_symmetry_flags_missing_permutes():
    inv = build_inventory(_spec(
        "lm_pipeline", shlo="module @jit_train_step {}", pipeline=True,
    ))
    fs = apply_rules(inv)
    assert [f.rule for f in fs] == ["pipeline-collective-symmetry"]
    assert "no collective-permute" in fs[0].message


def test_copy_hotspot_on_decode_pool():
    pool = 16 * 8 * 64 * 4
    good = build_inventory(_spec(
        "serve_decode", hlo=_fixture("decode_good.hlo.txt"),
        pool_bytes=pool,
    ))
    assert apply_rules(good) == []
    bad = build_inventory(_spec(
        "serve_decode", hlo=_fixture("decode_bad_copy.hlo.txt"),
        pool_bytes=pool,
    ))
    fs = apply_rules(bad)
    assert [f.rule for f in fs] == ["steady-state-copy-hotspot"]


def test_two_shape_fingerprint_diff():
    shlo = _fixture("pipeline_good.shlo.txt")
    same = _spec("lm_flat", hlo=_fixture("decode_good.hlo.txt"), shlo=shlo)
    same.alt_lowered = _FakeLowered(shlo)
    assert build_inventory(same).data["two_shape"] == "equal"

    specialized = _spec(
        "lm_flat", hlo=_fixture("decode_good.hlo.txt"), shlo=shlo,
    )
    specialized.alt_lowered = _FakeLowered(
        shlo + '\n%x = "stablehlo.reshape"()'
    )
    inv = build_inventory(specialized)
    assert inv.data["two_shape"] == "differs"
    assert [f.rule for f in apply_rules(inv)] == [
        "shape-specialized-constant",
    ]


# ---------------------------------------------------------------------------
# baseline: drift fails, shrink goes stale, round-trip is byte-stable
# ---------------------------------------------------------------------------


def _inv(name="cnn_dp", hlo=None, **kw):
    return build_inventory(_spec(
        name, hlo=hlo or _fixture("zero_good.hlo.txt"), **kw,
    ))


def test_baseline_roundtrip_byte_identical(tmp_path):
    path = tmp_path / "HLO_BASELINE.json"
    programs = {"cnn_dp": _inv().data}
    save_hlo_baseline(path, programs)
    first = path.read_bytes()
    assert load_hlo_baseline(path) == programs
    save_hlo_baseline(path, load_hlo_baseline(path))
    assert path.read_bytes() == first


def test_drift_new_collective_and_count_growth_fail():
    inv = _inv()
    base = {"cnn_dp": json.loads(json.dumps(inv.data))}
    # identical → no findings, no stale
    fs, stale = diff_baseline({"cnn_dp": inv}, base, scope=None)
    assert (fs, stale) == ([], [])
    # a collective kind the baseline never saw
    grown = json.loads(json.dumps(base))
    del grown["cnn_dp"]["collectives"]["all-reduce@data+model"]
    fs, _ = diff_baseline({"cnn_dp": inv}, grown, scope=None)
    assert [f.rule for f in fs] == ["hlo-drift-new-collective"]
    # count growth on a known key
    grown = json.loads(json.dumps(base))
    grown["cnn_dp"]["collectives"]["all-gather@data"]["count"] -= 1
    fs, _ = diff_baseline({"cnn_dp": inv}, grown, scope=None)
    assert [f.rule for f in fs] == ["hlo-drift-collective-count"]


def test_drift_bytes_tolerance_is_ten_percent():
    inv = _inv()
    base = json.loads(json.dumps({"cnn_dp": inv.data}))
    key = "all-gather@data"
    ent = base["cnn_dp"]["collectives"][key]
    # within 10%: fine (count must match, so only shrink bytes)
    ent["bytes"] = int(inv.data["collectives"][key]["bytes"] / 1.05)
    fs, _ = diff_baseline({"cnn_dp": inv}, base, scope=None)
    assert fs == []
    ent["bytes"] = int(inv.data["collectives"][key]["bytes"] / 1.5)
    fs, _ = diff_baseline({"cnn_dp": inv}, base, scope=None)
    assert [f.rule for f in fs] == ["hlo-drift-collective-bytes"]


def test_drift_lost_alias_fails():
    inv = _inv()
    base = json.loads(json.dumps({"cnn_dp": inv.data}))
    base["cnn_dp"]["aliases"] = [["0", 0, ""]]
    fs, _ = diff_baseline({"cnn_dp": inv}, base, scope=None)
    assert [f.rule for f in fs] == ["hlo-drift-lost-alias"]


def test_shrink_and_fingerprint_changes_go_stale_not_fail():
    inv = _inv()
    base = json.loads(json.dumps({"cnn_dp": inv.data}))
    # baseline remembers MORE traffic than the program now has → stale
    base["cnn_dp"]["collectives"]["all-gather@data"]["count"] += 3
    base["cnn_dp"]["fingerprint"] = "f" * 64
    fs, stale = diff_baseline({"cnn_dp": inv}, base, scope=None)
    assert fs == []
    assert len(stale) == 2


def test_unbaselined_and_unprobed_programs():
    inv = _inv()
    fs, stale = diff_baseline({"cnn_dp": inv}, {}, scope=None)
    assert [f.rule for f in fs] == ["hlo-unbaselined-program"]
    fs, stale = diff_baseline(
        {}, {"ghost": {"collectives": {}}}, scope=None,
    )
    assert fs == []
    assert any("ghost" in s for s in stale)
    # scoped run: out-of-scope baseline programs are not reported
    fs, stale = diff_baseline(
        {}, {"ghost": {"collectives": {}}}, scope={"cnn_dp"},
    )
    assert (fs, stale) == ([], [])


def test_findings_for_attributes_by_program():
    f1 = Finding("a.py", 1, "r", "cnn_dp: x")
    f2 = Finding("a.py", 1, "r", "lm_flat: y")
    assert findings_for([f1, f2], "cnn_dp") == [f1]


# ---------------------------------------------------------------------------
# probe registry / --changed mapping
# ---------------------------------------------------------------------------


def test_probe_registry_covers_every_family():
    names = probe_names()
    for expected in (
        "cnn_dp", "cnn_dp_zero", "cnn_dp_fused", "lm_flat", "lm_zero",
        "vit_flat", "lm_decode", "serve", "lm_pipeline",
        "lm_pipeline_zb", "vit_pipeline",
    ):
        assert expected in names


def test_affected_probes_maps_modules():
    assert affected_probes({"ddl_tpu.train.lm_steps"}) == [
        "lm_flat", "lm_zero",
    ]
    assert affected_probes({"ddl_tpu.serve.engine"}) == ["serve"]
    assert affected_probes({"ddl_tpu.obs.events"}) == []
    # every registered factory module is a real package module
    pkg = Path(__file__).resolve().parents[1]
    for _name, mod, _build in HLO_PROBES:
        assert (pkg / Path(*mod.split("."))).with_suffix(".py").exists(), mod
