"""Gradient accumulation (accum_steps in lm_steps / vit_steps).

Mean-CE gradients over equal chunks average to the full-batch gradient,
so the accumulated step must equal the plain step numerically.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from ddl_tpu.models.transformer import LMConfig
from ddl_tpu.models.vit import ViTConfig
from ddl_tpu.parallel.sharding import LMMeshSpec
from ddl_tpu.train.lm_steps import make_lm_step_fns
from ddl_tpu.train.vit_steps import make_vit_step_fns

B, T = 8, 8


def _maxdiff(a, b):
    return jax.tree.reduce(max, jax.tree.map(
        lambda x, y: float(np.max(np.abs(np.asarray(x) - np.asarray(y)))),
        jax.device_get(a), jax.device_get(b)))


def test_lm_accum_matches_plain():
    cfg = LMConfig(vocab_size=32, d_model=16, n_layers=2, n_heads=2,
                   head_dim=8, d_ff=32, compute_dtype="float32", remat=False)
    tx = optax.adam(1e-2)
    toks = jnp.asarray(np.random.default_rng(0).integers(0, 32, (B, T + 1)))
    inp, tgt = toks[:, :-1], toks[:, 1:]

    kwargs = dict(devices=jax.devices()[:2])
    plain = make_lm_step_fns(cfg, LMMeshSpec(data=2), tx, jax.random.key(0),
                             B, T, **kwargs)
    acc = make_lm_step_fns(cfg, LMMeshSpec(data=2), tx, jax.random.key(0),
                           B, T, accum_steps=4, **kwargs)
    s1, m1 = plain.train(plain.init_state(), inp, tgt)
    s2, m2 = acc.train(acc.init_state(), inp, tgt)
    assert abs(float(m1["loss"]) - float(m2["loss"])) < 1e-5
    assert _maxdiff(s1.params, s2.params) < 1e-5


def test_vit_accum_matches_plain():
    cfg = ViTConfig(image_size=16, patch_size=4, d_model=32, n_layers=2,
                    n_heads=4, head_dim=8, d_ff=64, compute_dtype="float32",
                    remat=False)
    tx = optax.adam(1e-2)
    rng = np.random.default_rng(1)
    imgs = jnp.asarray(rng.integers(0, 255, (B, 16, 16, 3)).astype(np.uint8))
    labels = jnp.asarray(rng.integers(0, 5, (B,)).astype(np.int32))

    kwargs = dict(devices=jax.devices()[:2])
    plain = make_vit_step_fns(cfg, LMMeshSpec(data=2), tx, jax.random.key(0),
                              B, **kwargs)
    acc = make_vit_step_fns(cfg, LMMeshSpec(data=2), tx, jax.random.key(0),
                            B, accum_steps=2, **kwargs)
    s1, m1 = plain.train(plain.init_state(), imgs, labels)
    s2, m2 = acc.train(acc.init_state(), imgs, labels)
    assert abs(float(m1["loss"]) - float(m2["loss"])) < 1e-5
    assert _maxdiff(s1.params, s2.params) < 1e-5


def test_accum_validation():
    cfg = LMConfig(vocab_size=32, d_model=16, n_layers=2, n_heads=2,
                   head_dim=8, d_ff=32, compute_dtype="float32", remat=False)
    tx = optax.adam(1e-2)
    with pytest.raises(ValueError, match="accum_steps"):
        make_lm_step_fns(cfg, LMMeshSpec(data=1), tx, jax.random.key(0),
                         B, T, accum_steps=3, devices=jax.devices()[:1])
    with pytest.raises(ValueError, match="num_microbatches instead"):
        make_lm_step_fns(cfg, LMMeshSpec(pipe=2), tx, jax.random.key(0),
                         B, T, accum_steps=2, devices=jax.devices()[:2])
    # < 1 is rejected on the pipelined path too (check hoisted above dispatch)
    with pytest.raises(ValueError, match=">= 1"):
        make_lm_step_fns(cfg, LMMeshSpec(pipe=2), tx, jax.random.key(0),
                         B, T, accum_steps=0, devices=jax.devices()[:2])