"""Int8 serving quantization (ops/quant.py): KV cache + weight-only.

Parity discipline: quantization is a *lossy* compression of HBM traffic,
so these tests pin the loss — element-wise error bounded by the absmax
scale, end-to-end logits within small relative error of the exact path,
and greedy decode agreeing on (almost) every token.  The exact-math
pieces (scale folding, ring slots, window slices, GQA grouping) are
tested exactly.  (The reference has no inference quantization — or any
generation path — at all; the bar here is this repo's own bf16 decode.)
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ddl_tpu.infer import LMDecode, init_kv_cache, make_lm_generator
from ddl_tpu.models.transformer import LMConfig, TransformerLM
from ddl_tpu.ops.attention import dense_attention
from ddl_tpu.ops.quant import (
    QuantKV,
    dequantize_q8,
    quant_dense_attention,
    quantize_lm_params,
    quantize_q8,
)


def _cfg(**kw):
    base = dict(
        vocab_size=64,
        d_model=32,
        n_layers=2,
        n_heads=4,
        head_dim=8,
        d_ff=64,
        compute_dtype="float32",
        attn_impl="dense",
        remat=False,
    )
    base.update(kw)
    return LMConfig(**base)


def _params(cfg, batch=2, t=8, seed=0):
    import flax.linen as nn

    model = TransformerLM(cfg, None)
    dummy = jnp.zeros((batch, t), jnp.int32)
    return nn.meta.unbox(model.init(jax.random.key(seed), dummy)["params"])


def test_quantize_roundtrip_error_bound():
    """|x - dequant(quant(x))| <= scale/2 element-wise (round-to-nearest)."""
    x = jax.random.normal(jax.random.key(0), (4, 16, 3, 32)) * 3.0
    q, s = quantize_q8(x)
    err = np.abs(np.asarray(x) - np.asarray(dequantize_q8(q, s)))
    assert np.all(err <= np.asarray(s) / 2 + 1e-7)
    assert q.dtype == jnp.int8 and s.dtype == jnp.float32


def test_quant_attention_matches_dequantized_reference():
    """quant_dense_attention == dense_attention over the dequantized cache
    (same math, scales folded into scores/probs instead)."""
    rng = np.random.default_rng(0)
    b, tq, L, h, hkv, d = 2, 3, 16, 8, 4, 16
    q = jnp.asarray(rng.normal(size=(b, tq, h, d)), jnp.float32)
    k = jnp.asarray(rng.normal(size=(b, L, hkv, d)), jnp.float32)
    v = jnp.asarray(rng.normal(size=(b, L, hkv, d)), jnp.float32)
    kq, ks = quantize_q8(k)
    vq, vs = quantize_q8(v)
    mask = jnp.asarray(rng.random((tq, L)) > 0.3)
    mask = mask.at[:, 0].set(True)  # no fully-masked row
    got = quant_dense_attention(
        q, kq, ks[..., 0].transpose(0, 2, 1), vq,
        vs[..., 0].transpose(0, 2, 1), mask,
    )  # scales are (B, Hkv, L) in cache layout
    want = dense_attention(
        q, dequantize_q8(kq, ks), dequantize_q8(vq, vs), mask=mask
    )
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), atol=2e-5, rtol=1e-4
    )


def test_kv_quant_incremental_close_to_exact():
    """Token-by-token decode with the int8 cache tracks the full forward's
    logits within int8-level error at every position."""
    cfg = _cfg()
    b, t = 2, 7
    params = _params(cfg, b, t)
    toks = jnp.asarray(np.random.default_rng(1).integers(0, 64, (b, t)))
    ref_logits, _ = TransformerLM(cfg, None).apply({"params": params}, toks)

    dec = LMDecode(cfg)
    caches = init_kv_cache(cfg, b, t, quant=True)
    assert isinstance(caches[0], QuantKV)
    got = []
    for i in range(t):
        logits, caches = dec.apply(
            {"params": params}, toks[:, i : i + 1], caches, i
        )
        got.append(np.asarray(logits[:, 0]))
    got = np.stack(got, 1)
    ref = np.asarray(ref_logits)
    # int8 cache error, bounded relative to the logit scale
    assert np.max(np.abs(got - ref)) / (np.abs(ref).max() + 1e-9) < 0.05
    # and the argmax (greedy token) agrees nearly everywhere
    agree = (got.argmax(-1) == ref.argmax(-1)).mean()
    assert agree >= 0.9


@pytest.mark.parametrize(
    "kw",
    [
        {},  # MHA, full cache
        {"n_kv_heads": 2},  # GQA
        {"attn_window": 6},  # windowed (rolling ring cache auto-on)
    ],
    ids=["mha", "gqa", "window"],
)
def test_kv_quant_generator_matches_bf16_generator(kw):
    """The full jitted generator (prefill + scan) with kv_quant=True
    produces (nearly) the same greedy tokens as the exact cache."""
    cfg = _cfg(**kw)
    b, p, n = 2, 8, 12
    params = _params(cfg, b, p)
    prompt = jnp.asarray(np.random.default_rng(2).integers(0, 64, (b, p)))
    gen = make_lm_generator(cfg, prompt_len=p, max_new=n, batch=b)
    gen_q = make_lm_generator(
        cfg, prompt_len=p, max_new=n, batch=b, kv_quant=True
    )
    t_ref = np.asarray(gen(params, prompt))
    t_q = np.asarray(gen_q(params, prompt))
    assert (t_ref == t_q).mean() >= 0.8, (t_ref, t_q)


def test_weight_quant_forward_close():
    """quantize_lm_params tree applies through the SAME modules (QDense /
    LMHead sniff the scale leaves) and tracks the f32 forward."""
    cfg = _cfg()
    b, t = 2, 8
    params = _params(cfg, b, t)
    toks = jnp.asarray(np.random.default_rng(3).integers(0, 64, (b, t)))
    qparams = quantize_lm_params(params)
    # every matmul kernel went int8 + scale; norms/embed/router untouched
    assert qparams["block0"]["attn"]["q"]["kernel"].dtype == jnp.int8
    assert qparams["block0"]["attn"]["q"]["scale"].shape == (
        1, cfg.n_heads * cfg.head_dim,
    )
    assert qparams["lm_head"]["kernel"].dtype == jnp.int8
    assert qparams["lm_head"]["scale"].shape == (cfg.vocab_size, 1)
    assert qparams["embed"]["embedding"].dtype == jnp.float32
    assert qparams["norm_f"]["scale"].dtype == jnp.float32

    ref, _ = TransformerLM(cfg, None).apply({"params": params}, toks)
    got, _ = TransformerLM(cfg, None).apply({"params": qparams}, toks)
    ref, got = np.asarray(ref), np.asarray(got)
    assert np.max(np.abs(got - ref)) / (np.abs(ref).max() + 1e-9) < 0.08
    assert (got.argmax(-1) == ref.argmax(-1)).mean() >= 0.9


def test_weight_quant_moe_forward_close():
    """Expert banks quantize per (expert, out-channel) and the MoE layer
    dequants via the wi_scale/wo_scale leaves."""
    cfg = _cfg(num_experts=4, expert_top_k=2, moe_group=0)
    b, t = 2, 8
    params = _params(cfg, b, t)
    toks = jnp.asarray(np.random.default_rng(4).integers(0, 64, (b, t)))
    qparams = quantize_lm_params(params)
    moe = qparams["block0"]["moe"]
    assert moe["wi"].dtype == jnp.int8
    assert moe["wi_scale"].shape == (4, 1, cfg.d_ff)
    assert moe["router"]["kernel"].dtype == jnp.float32  # routing exact

    ref, _ = TransformerLM(cfg, None).apply({"params": params}, toks)
    got, _ = TransformerLM(cfg, None).apply({"params": qparams}, toks)
    ref, got = np.asarray(ref), np.asarray(got)
    assert np.max(np.abs(got - ref)) / (np.abs(ref).max() + 1e-9) < 0.08


def test_weight_and_kv_quant_generator():
    """The full int8 serving path: int8 weights AND int8 cache through the
    jitted generator, vs the exact generator."""
    cfg = _cfg(n_kv_heads=2, attn_window=10)
    b, p, n = 2, 8, 12
    params = _params(cfg, b, p)
    prompt = jnp.asarray(np.random.default_rng(5).integers(0, 64, (b, p)))
    gen = make_lm_generator(cfg, prompt_len=p, max_new=n, batch=b)
    gen_q = make_lm_generator(
        cfg, prompt_len=p, max_new=n, batch=b, kv_quant=True
    )
    t_ref = np.asarray(gen(params, prompt))
    t_q = np.asarray(gen_q(quantize_lm_params(params), prompt))
    assert (t_ref == t_q).mean() >= 0.7, (t_ref, t_q)


def test_head_kernel_accessor_dequants():
    """The chunked-CE paths read the head kernel via ops.quant.head_kernel
    — on an int8 tree it must hand back the dequantized f32 kernel, not
    the raw int8 (which would silently drop the per-row scales)."""
    cfg = _cfg()
    from ddl_tpu.ops.quant import head_kernel

    params = _params(cfg)
    qparams = quantize_lm_params(params)
    got = head_kernel(qparams["lm_head"])
    ref = params["lm_head"]["kernel"]
    assert got.dtype == jnp.float32
    err = np.abs(np.asarray(got) - np.asarray(ref))
    assert err.max() <= np.asarray(qparams["lm_head"]["scale"]).max() / 2 + 1e-7
    # exact passthrough on an unquantized tree
    assert head_kernel(params["lm_head"]) is ref


def test_ce_chunk_eval_with_quantized_params():
    """Teacher-forced eval through the token-chunked CE edge on an int8
    tree matches the dense-CE eval of the same tree (the path the review
    flagged: the chunked edge bypasses LMHead's scale sniffing)."""
    cfg = _cfg(ce_chunk=4)
    b, t = 2, 8
    params = _params(cfg, b, t)
    qparams = quantize_lm_params(params)
    toks = jnp.asarray(np.random.default_rng(6).integers(0, 64, (b, t)))
    tgts = jnp.asarray(np.random.default_rng(7).integers(0, 64, (b, t)))
    from ddl_tpu.train.lm_steps import chunked_ce_loss
    from ddl_tpu.ops.quant import head_kernel

    hidden, aux = TransformerLM(cfg, None).apply(
        {"params": qparams}, toks, return_hidden=True
    )
    loss, _ = chunked_ce_loss(
        cfg, hidden, head_kernel(qparams["lm_head"]), tgts, aux, False
    )
    # dense-CE reference over the same quantized tree
    logits, _ = TransformerLM(
        dataclasses_replace(cfg, ce_chunk=0), None
    ).apply({"params": qparams}, toks)
    lp = jax.nn.log_softmax(logits.astype(jnp.float32), -1)
    ref = -jnp.take_along_axis(lp, tgts[..., None], -1).mean()
    np.testing.assert_allclose(float(loss), float(ref), rtol=1e-5)


def dataclasses_replace(cfg, **kw):
    import dataclasses

    return dataclasses.replace(cfg, **kw)


def test_quantize_boxed_tree_and_empty_tree():
    """A fresh (boxed) init tree quantizes — no silent no-op — and a tree
    with nothing to quantize raises."""
    import flax.linen as nn

    cfg = _cfg()
    boxed = TransformerLM(cfg, None).init(
        jax.random.key(0), jnp.zeros((1, 4), jnp.int32)
    )["params"]  # NOT unboxed
    q = quantize_lm_params(boxed)
    assert q["block0"]["attn"]["q"]["kernel"].dtype == jnp.int8
    with pytest.raises(ValueError, match="no matmul kernel"):
        quantize_lm_params({"norm": {"scale": jnp.ones((4,))}})


def test_quant_cache_bytes_halved():
    """The allocation claim behind the bench rows: int8 cache bytes ≈
    0.53x bf16 (int8 payload + 1 f32 scale per head_dim values)."""
    cfg = _cfg(compute_dtype="bfloat16")
    bf16 = jax.eval_shape(lambda: init_kv_cache(cfg, 4, 128))
    q8 = jax.eval_shape(lambda: init_kv_cache(cfg, 4, 128, quant=True))
    nbytes = lambda tree: sum(
        int(np.prod(a.shape)) * a.dtype.itemsize
        for a in jax.tree_util.tree_leaves(tree)
    )
    ratio = nbytes(q8) / nbytes(bf16)
    assert abs(ratio - (0.5 + 4 / (2 * cfg.head_dim))) < 1e-6
