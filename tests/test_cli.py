"""CLI end-to-end: preset + dotted overrides drive a full tiny training run."""

import numpy as np

from ddl_tpu.utils.csv_logger import read_metric_csv


def test_cli_single_end_to_end(tmp_path, monkeypatch):
    from ddl_tpu import cli

    monkeypatch.setenv("DDL_JOB_ID", "single-clitest")
    cli.main(
        [
            "--preset",
            "single",
            "--set",
            "model.growth_rate=4",
            "model.block_config=[2,2]",
            "model.num_init_features=8",
            "model.bn_size=2",
            "model.split_blocks=[1]",
            "model.remat=false",
            "data.image_size=16",
            "data.synthetic_num_train=32",
            "data.synthetic_num_test=16",
            "data.global_batch_size=8",
            "data.eval_batch_size=8",
            "data.num_workers=0",
            "train.max_epochs=1",
            f"train.log_dir={tmp_path}/logs",
            f"train.checkpoint_dir={tmp_path}/ckpt",
        ]
    )
    rows = read_metric_csv(tmp_path / "logs" / "by_job_id" / "single-clitest" / "loss.csv")
    assert len(rows) == 1 and np.isfinite(rows[0]["value"])
    sps = read_metric_csv(
        tmp_path / "logs" / "by_job_id" / "single-clitest" / "steps_per_sec.csv"
    )
    assert sps[0]["value"] > 0
