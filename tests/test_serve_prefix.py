"""Shared-prefix KV reuse, chunked prefill, and the scenario matrix
(round 17, `ddl_tpu/serve/`).

Host tier (no JAX): refcounted-allocator invariants under
allocate/share/free/defrag (no block freed while referenced, no leak
after all owners retire, double-free raises), prefix-index chain
lookup/insert/LRU eviction at the allocation watermark, the
prefix-aware admission accounting (a fully-cached request admits into a
pool sized below its nominal footprint — the round-17 bugfix), and the
obs fold's prefix counters (sidecar v6, warm==cold preserved).

Device tier (CPU JAX, slow): shared-prefix clients bit-identical to
cache-off AND to sequential `make_lm_generator` runs in greedy/sampled
variants; int8 prefix reuse at documented quantization tolerance;
copy-on-write on fully-cached block-aligned prompts; chunked prefill
interleaving decode dispatches (a long prompt cannot stall short
requests); eviction under pool pressure; the serve-bench --scenario CLI
with the exact --compare-sequential gate; deterministic 1-in-N trace
sampling.
"""

import numpy as np
import pytest


# ---------------------------------------------------------------------------
# host tier: refcounted allocator
# ---------------------------------------------------------------------------


def test_allocator_refcount_share_free():
    from ddl_tpu.serve.kv_pool import BlockAllocator

    a = BlockAllocator(8, 4)
    x = a.alloc(3)  # refcount 1 each
    a.share(x[:2])  # a second owner for blocks 0, 1
    assert [a.refcount(b) for b in x] == [2, 2, 1]
    # first owner retires: referenced blocks stay allocated
    a.free(x)
    assert a.used_blocks == 2 and a.free_blocks == 6
    assert [a.refcount(b) for b in x] == [1, 1, 0]
    # freeing the unreferenced block again is a double free
    with pytest.raises(ValueError):
        a.free([x[2]])
    # second owner retires: no leak — everything back in circulation
    a.free(x[:2])
    assert a.used_blocks == 0 and a.free_blocks == 8
    # sharing a free block is a bookkeeping bug
    with pytest.raises(ValueError):
        a.share([x[0]])
    # invariant held throughout
    assert a.free_blocks + a.used_blocks + a.cached_blocks == 8


def test_allocator_evictable_lru_eviction():
    from ddl_tpu.serve.kv_pool import BlockAllocator

    a = BlockAllocator(4, 4)
    evicted = []
    a.on_evict = evicted.append
    x = a.alloc(3)
    for b in x:
        a.mark_indexed(b)
    # release in a known order -> LRU order 0, 1, 2
    a.free([x[0]])
    a.free([x[1]])
    a.free([x[2]])
    assert a.used_blocks == 0 and a.cached_blocks == 3
    assert a.free_blocks == 1  # cached blocks are NOT free
    assert a.can_alloc(4)  # ... but they are allocatable via eviction
    # allocating 3 takes the free block + evicts the 2 least-recently
    # released cached blocks, notifying the index hook
    y = a.alloc(3)
    assert evicted == [x[0], x[1]]
    assert a.evictions == 2
    assert a.cached_blocks == 1 and x[2] not in y
    # reactivating the surviving cached block via share
    a.share([x[2]])
    assert a.refcount(x[2]) == 1 and a.cached_blocks == 0
    a.free([x[2]])
    assert a.cached_blocks == 1  # still indexed -> parks again
    a.drop_indexed(x[2])  # explicit index invalidation frees it
    assert a.cached_blocks == 0 and a.free_blocks == 1
    a.free(y)
    assert a.free_blocks + a.used_blocks + a.cached_blocks == 4


def test_allocator_compaction_with_cached_blocks():
    from ddl_tpu.serve.kv_pool import BlockAllocator, PrefixIndex

    a = BlockAllocator(8, 4)
    idx = PrefixIndex(4)
    a.on_evict = idx.forget_block
    toks = np.arange(8, dtype=np.int32)
    x = a.alloc(2)  # [0, 1]
    y = a.alloc(2)  # [2, 3]
    idx.insert(toks, y, a)  # blocks 2, 3 hold toks' two full blocks
    a.free(x)  # holes at 0, 1
    a.free(y)  # 2, 3 -> evictable (indexed), content retained
    assert a.cached_blocks == 2
    plan = a.compaction_plan()
    assert plan == {2: 0, 3: 1}  # cached blocks are live content: packed
    idx.remap(plan)
    a.commit_plan(plan)
    assert idx.lookup(toks) == [0, 1]
    assert a.cached_blocks == 2 and a.free_blocks == 6


# ---------------------------------------------------------------------------
# host tier: prefix index
# ---------------------------------------------------------------------------


def test_prefix_index_chain_lookup():
    from ddl_tpu.serve.kv_pool import BlockAllocator, PrefixIndex

    a = BlockAllocator(16, 4)
    idx = PrefixIndex(4)
    p1 = np.arange(10, dtype=np.int32)  # 2 full blocks + 2 tail tokens
    b1 = a.alloc(3)
    assert idx.insert(p1, b1, a) == 2  # only FULL blocks registered
    # same first block, different second block -> 1-block chain only
    p2 = np.concatenate([p1[:4], p1[4:8] + 1, p1[8:]])
    assert idx.lookup(p1) == b1[:2]
    assert idx.lookup(p2) == b1[:1]
    # chain hash commits to the WHOLE prefix: same tokens in block 1 but
    # a different block 0 must not chain onto b1[1]
    p3 = np.concatenate([p1[:4] + 1, p1[4:8]])
    assert idx.lookup(p3) == []
    # first writer wins: re-inserting the same content registers nothing
    b2 = a.alloc(3)
    assert idx.insert(p1, b2, a) == 0
    # eviction hook forgets the block and breaks the chain there
    idx.forget_block(b1[1])
    assert idx.lookup(p1) == b1[:1]


# ---------------------------------------------------------------------------
# host tier: prefix-aware admission (the round-17 accounting fix)
# ---------------------------------------------------------------------------


def _req(rid, prompt, max_new):
    from ddl_tpu.serve.scheduler import Request

    return Request(
        id=rid, prompt=np.asarray(prompt, np.int32), max_new=max_new
    )


def test_admit_charges_private_demand_only():
    from ddl_tpu.serve.kv_pool import BlockAllocator, PrefixIndex
    from ddl_tpu.serve.scheduler import ContinuousScheduler

    a = BlockAllocator(8, 4)
    idx = PrefixIndex(4)
    a.on_evict = idx.forget_block
    s = ContinuousScheduler(a, max_batch=4, max_blocks_per_seq=8,
                            prefix_index=idx)
    prefix = np.arange(8, dtype=np.int32)  # 2 full blocks
    first = s.try_admit(_req("a", np.concatenate([prefix, [9, 9]]), 3))
    idx.insert(first.request.prompt, first.block_ids, a)
    used_before = a.used_blocks
    # second request shares the 2 prefix blocks read-only and allocates
    # only its private remainder: 12 rows -> 3 blocks total, 1 private
    second = s.try_admit(_req("b", np.concatenate([prefix, [7, 7]]), 3))
    assert second.cached_tokens == 8 and second.shared_blocks == 2
    assert second.block_ids[:2] == first.block_ids[:2]
    assert a.used_blocks == used_before + 1  # ONE private block
    assert a.refcount(first.block_ids[0]) == 2
    # retire in either order: shared blocks survive until the last owner
    s.retire(first.lane)
    assert a.refcount(second.block_ids[0]) == 1
    s.retire(second.lane)
    # all owners gone: indexed blocks park evictable, rest freed
    assert a.used_blocks == 0
    assert a.cached_blocks == 2  # the two indexed prefix blocks
    assert a.free_blocks + a.cached_blocks == 8


def test_fits_ever_fully_cached_regression():
    """The round-17 admission bugfix: a request whose prefix is fully
    cached must NOT be rejected (or parked forever) for a worst-case
    footprint it will never allocate."""
    from ddl_tpu.serve.kv_pool import BlockAllocator, PrefixIndex
    from ddl_tpu.serve.scheduler import ContinuousScheduler

    # (1) residency envelope (review round 2): sharing shrinks what a
    # request ALLOCATES, never the blocks it needs to exist — a
    # 6-residency request must be rejected by a 5-block pool even with
    # its prefix fully cached (fits_ever=True there would park it at
    # the queue head forever: can_admit can never beat
    # num_blocks - shared_n headroom, and run() livelocks)
    a0 = BlockAllocator(5, 4)
    idx0 = PrefixIndex(4)
    s0 = ContinuousScheduler(a0, max_batch=2, max_blocks_per_seq=8,
                             prefix_index=idx0)
    prefix = np.arange(16, dtype=np.int32)  # 4 full blocks
    prompt = np.concatenate([prefix, [1, 1]])  # 18 tokens
    big = _req("big", prompt, 4)  # 21 rows -> 6 blocks nominal
    assert s0.blocks_needed(big) == 6
    assert not s0.fits_ever(big)  # nothing cached: can never fit 5
    owner0 = s0.try_admit(_req("o", prompt, 3))  # 5 blocks
    idx0.insert(prompt, owner0.block_ids, a0)
    assert not s0.fits_ever(big)  # still 4 shared + 2 private > 5
    # (2) live sharing — the actual round-17 win: with the owner still
    # RESIDENT (5 of 8 blocks), worst-case accounting sees 6 needed >
    # 3 free and parks the request forever; charging only the private
    # demand admits it immediately (the shared prefix counts against
    # the pool once, not once per request)
    a = BlockAllocator(8, 4)
    idx = PrefixIndex(4)
    s = ContinuousScheduler(a, max_batch=2, max_blocks_per_seq=8,
                            prefix_index=idx)
    owner = s.try_admit(_req("o", prompt, 3))
    idx.insert(prompt, owner.block_ids, a)
    assert a.free_blocks == 3  # < the nominal 6-block footprint
    assert s.can_admit(big)
    st = s.try_admit(big)
    assert st is not None and st.cached_tokens == 16
    assert st.shared_blocks == 4
    assert a.free_blocks == 1  # only the 2 private blocks were drawn
    # (3) review round 3: a fully-cached block-aligned prompt that fits
    # the pool EXACTLY must not become unadmittable because the CoW
    # recompute would charge one extra resident block — the chain is
    # capped (last cached block dropped and recomputed) instead
    a3 = BlockAllocator(3, 4)
    idx3 = PrefixIndex(4)
    a3.on_evict = idx3.forget_block
    s3 = ContinuousScheduler(a3, max_batch=2, max_blocks_per_seq=4,
                             prefix_index=idx3)
    p8 = np.arange(8, dtype=np.int32)  # exactly 2 blocks
    exact = _req("exact", p8, 5)  # 12 rows -> ALL 3 pool blocks
    first = s3.try_admit(_req("o", p8, 5))
    idx3.insert(p8, first.block_ids, a3)
    s3.retire(first.lane)
    again = _req("again", p8, 5)
    assert s3.fits_ever(again)  # capped chain: residency == need == 3
    st3 = s3.try_admit(again)
    assert st3 is not None
    assert st3.cow_block is None  # fell back to recompute, not CoW
    assert st3.shared_blocks == 1 and st3.cached_tokens == 4
    del exact


def test_fully_cached_aligned_prompt_reserves_cow_target():
    from ddl_tpu.serve.kv_pool import BlockAllocator, PrefixIndex
    from ddl_tpu.serve.scheduler import ContinuousScheduler

    a = BlockAllocator(8, 4)
    idx = PrefixIndex(4)
    s = ContinuousScheduler(a, max_batch=2, max_blocks_per_seq=8,
                            prefix_index=idx)
    prompt = np.arange(8, dtype=np.int32)  # exactly 2 blocks
    owner = s.try_admit(_req("o", prompt, 3))
    idx.insert(prompt, owner.block_ids, a)
    st = s.try_admit(_req("b", prompt, 3))
    # whole prompt cached: re-prefill the last BLOCK (block-aligned
    # chunk start) into a pre-allocated private copy of the last
    # shared block
    assert st.cached_tokens == 4  # prompt_len - block_size
    assert st.prefill_pos == 4 and not st.prefill_done
    assert st.cow_block is not None
    assert st.shared_blocks == 2


# ---------------------------------------------------------------------------
# host tier: obs fold prefix counters (sidecar v6)
# ---------------------------------------------------------------------------


def test_fold_prefix_counters_and_summary(tmp_path):
    import json

    from ddl_tpu.obs.fold import fold_job
    from ddl_tpu.obs.report import render_summary, summarize_from_fold

    d = tmp_path / "by_job_id" / "j"
    d.mkdir(parents=True)
    events = [
        {"ts": 1.0, "run": "r", "host": 0, "kind": "serve_admit",
         "cached_tokens": 0, "prefill_tokens": 10, "prompt_len": 10},
        {"ts": 2.0, "run": "r", "host": 0, "kind": "prefix_insert",
         "blocks": 1, "tokens": 8},
        {"ts": 3.0, "run": "r", "host": 0, "kind": "serve_admit",
         "cached_tokens": 8, "prefill_tokens": 2, "prompt_len": 10},
        {"ts": 3.1, "run": "r", "host": 0, "kind": "prefix_hit",
         "cached_tokens": 8, "blocks": 1},
        {"ts": 4.0, "run": "r", "host": 0, "kind": "kv_cow_copy",
         "src": 1, "dst": 5},
    ]
    (d / "events-h000.jsonl").write_text(
        "".join(json.dumps(e) + "\n" for e in events)
    )
    summary = summarize_from_fold(fold_job(tmp_path, "j"))
    sv = summary["serve"]
    assert sv["admits"] == 2 and sv["prefix_hits"] == 1
    assert sv["cached_tokens"] == 8 and sv["prefill_tokens"] == 12
    assert sv["prefix_hit_rate"] == pytest.approx(8 / 20)
    assert sv["cow_copies"] == 1 and sv["prefix_inserts"] == 1
    text = render_summary(summary, "j")
    assert "prefix cache: 1 hit(s)" in text
    # warm (sidecar) fold renders byte-identically to a cold parse
    warm = render_summary(
        summarize_from_fold(fold_job(tmp_path, "j")), "j"
    )
    cold = render_summary(
        summarize_from_fold(fold_job(tmp_path, "j", cache=False)), "j"
    )
    assert warm == cold == text


# ---------------------------------------------------------------------------
# device tier (CPU JAX)
# ---------------------------------------------------------------------------


def _tiny_cfg(**kw):
    from ddl_tpu.models.transformer import LMConfig

    base = dict(
        vocab_size=256, d_model=64, n_layers=2, n_heads=8, head_dim=8,
        d_ff=256, compute_dtype="float32",
    )
    base.update(kw)
    return LMConfig(**base)


@pytest.fixture(scope="module")
def lm():
    import flax.linen as nn
    import jax
    import jax.numpy as jnp

    from ddl_tpu.models.transformer import TransformerLM
    from ddl_tpu.parallel.sharding import LMMeshSpec

    cfg = _tiny_cfg()
    params = nn.meta.unbox(
        TransformerLM(cfg, None).init(
            jax.random.key(0), jnp.zeros((1, 8), jnp.int32)
        )["params"]
    )
    return cfg, params, LMMeshSpec()


def _sequential_tokens(cfg, spec, params, clients, seed, **gen_kw):
    import jax
    import jax.numpy as jnp

    from ddl_tpu.infer.decode import make_lm_generator

    out, gens = {}, {}
    for cid, prompt, mn in clients:
        key = (len(prompt), mn)
        if key not in gens:
            gens[key] = make_lm_generator(
                cfg, spec, prompt_len=len(prompt), max_new=mn, batch=1,
                **gen_kw,
            )
        toks = gens[key](
            params, jnp.asarray(prompt[None, :]), jax.random.PRNGKey(seed)
        )
        out[cid] = np.asarray(toks)[0]
    return out


def _shared_prefix_clients(n, prefix_len=24, seed=5):
    rng = np.random.default_rng(seed)
    prefix = rng.integers(0, 256, prefix_len).astype(np.int32)
    return [
        (
            f"c{i}",
            np.concatenate(
                [prefix,
                 rng.integers(0, 256, int(rng.integers(3, 10)))
                 .astype(np.int32)]
            ),
            int(rng.integers(4, 9)),
        )
        for i in range(n)
    ]


def _drive(cfg, params, spec, clients, *, seed=3, **engine_kw):
    from ddl_tpu.serve.engine import ServeEngine

    eng = ServeEngine(cfg, params, spec, block_size=8, num_blocks=64,
                      max_batch=4, **engine_kw)
    for cid, prompt, mn in clients:
        eng.submit(prompt, mn, request_id=cid, rng_seed=seed)
    return eng, eng.run()


@pytest.mark.parametrize(
    "kw",
    [dict(), dict(temperature=0.8, top_k=17)],
    ids=["greedy", "sampled"],
)
def test_shared_prefix_bit_identical(lm, kw):
    """THE round-17 acceptance e2e: shared-prefix clients through the
    engine with the prefix cache ON are bit-identical to the cache-OFF
    engine AND to one-at-a-time `make_lm_generator` replays — reuse
    changes scheduling and footprint, never tokens."""
    cfg, params, spec = lm
    clients = _shared_prefix_clients(6)
    eng_on, got_on = _drive(cfg, params, spec, clients, **kw)
    eng_off, got_off = _drive(
        cfg, params, spec, clients, prefix_cache=False, **kw
    )
    want = _sequential_tokens(cfg, spec, params, clients, seed=3, **kw)
    for cid in want:
        np.testing.assert_array_equal(got_on[cid], want[cid])
        np.testing.assert_array_equal(got_off[cid], want[cid])
    # the cache actually did something: every client after the first
    # hit the 24-token (3-block) shared prefix
    assert eng_on.stats["prefix_hits"] == 5
    assert eng_on.stats["prefix_hit_tokens"] == 5 * 24
    assert eng_on.stats["prefill_tokens"] < eng_off.stats["prefill_tokens"]
    assert eng_off.stats["prefix_hits"] == 0
    # all owners retired: shared blocks parked evictable, none leaked
    assert eng_on.allocator.used_blocks == 0
    assert eng_on.allocator.cached_blocks > 0
    a = eng_on.allocator
    assert a.free_blocks + a.cached_blocks == a.num_blocks


def test_fully_cached_prompt_cow_bit_identical(lm):
    """Identical block-aligned prompts: the repeat requests share every
    prompt block, copy-on-write duplicates the last one for the
    last-block recompute, and tokens stay bit-identical."""
    cfg, params, spec = lm
    prompt = np.arange(1, 17, dtype=np.int32)  # exactly 2 blocks of 8
    clients = [(f"c{i}", prompt, 5) for i in range(3)]
    eng, got = _drive(cfg, params, spec, clients)
    want = _sequential_tokens(cfg, spec, params, clients, seed=3)
    for cid in want:
        np.testing.assert_array_equal(got[cid], want[cid])
    assert eng.stats["cow_copies"] == 2  # one per repeat request
    assert eng.stats["prefix_hits"] == 2
    # each repeat recomputed exactly its LAST BLOCK (8 tokens)
    assert eng.stats["prefill_tokens"] == 16 + 2 * 8


def test_fully_cached_max_new_one_bit_identical(lm):
    """Regression (review round 5): the fully-cached recompute with
    max_new=1 sizes the gathered view at exactly the reservation — the
    old unaligned single-row chunk overflowed it (off=63 + an 8-row
    bucket against a 64-row view) and dynamic_update_slice clamped the
    start, corrupting attended rows.  Block-aligned recompute fits."""
    cfg, params, spec = lm
    prompt = np.arange(0, 64, dtype=np.int32)  # exactly 8 blocks of 8
    clients = [("a", prompt, 1), ("b", prompt, 1)]
    eng, got = _drive(cfg, params, spec, clients)
    want = _sequential_tokens(cfg, spec, params, clients, seed=3)
    for cid in want:
        np.testing.assert_array_equal(got[cid], want[cid])
    assert eng.stats["cow_copies"] == 1
    assert eng.stats["prefix_hits"] == 1


def test_int8_prefix_reuse_within_tolerance(lm):
    """int8 pools store K/V lossily, so a reused prefix is attended at
    quantization precision while a fresh prefill attends the raw
    activations — prefix reuse there is an explicit opt-in and is
    token-ACCURATE, not bit-identical (the same tolerance class as int8
    itself vs f32; see ARCHITECTURE.md).  Cache-off int8 stays exact."""
    cfg, params, spec = lm
    clients = _shared_prefix_clients(5)
    # auto default: int8 engines do NOT enable the prefix cache
    from ddl_tpu.serve.engine import ServeEngine

    auto = ServeEngine(cfg, params, spec, block_size=8, num_blocks=64,
                       max_batch=4, kv_quant=True)
    assert auto.prefix is None
    eng_off, got_off = _drive(
        cfg, params, spec, clients, kv_quant=True, prefix_cache=False
    )
    want = _sequential_tokens(
        cfg, spec, params, clients, seed=3, kv_quant=True
    )
    for cid in want:
        np.testing.assert_array_equal(got_off[cid], want[cid])
    # explicit opt-in: runs to completion, hits the cache, and agrees
    # with the exact reference on (nearly) every greedy token
    eng_on, got_on = _drive(
        cfg, params, spec, clients, kv_quant=True, prefix_cache=True
    )
    assert eng_on.stats["prefix_hits"] >= 4
    total = agree = 0
    for cid in want:
        total += len(want[cid])
        agree += int((got_on[cid] == want[cid]).sum())
    assert agree / total >= 0.7, (agree, total)


def test_chunked_prefill_interleaves_decode(lm):
    """A long prompt under `prefill_chunk` runs as bounded chunks with
    decode dispatches BETWEEN them: a short request admitted alongside
    finishes while the long prompt is still prefilling, and tokens stay
    bit-identical to the sequential replay."""
    cfg, params, spec = lm
    from ddl_tpu.serve.engine import ServeEngine

    rng = np.random.default_rng(9)
    long_prompt = rng.integers(0, 256, 96).astype(np.int32)
    short_prompt = rng.integers(0, 256, 7).astype(np.int32)
    eng = ServeEngine(cfg, params, spec, block_size=8, num_blocks=64,
                      max_batch=4, prefill_chunk=16, prefix_cache=False)
    eng.submit(long_prompt, 4, request_id="long", rng_seed=3)
    eng.submit(short_prompt, 3, request_id="short", rng_seed=3)
    short_done_at = long_prefill_done_at = None
    steps = 0
    while eng.step():
        steps += 1
        if short_done_at is None and "short" in eng.results:
            short_done_at = steps
        lane = next(
            (s for s in eng.scheduler.active()
             if s.request.id == "long"), None
        )
        if long_prefill_done_at is None and (
            lane is None or lane.prefill_done
        ):
            long_prefill_done_at = steps
    assert eng.stats["prefill_chunks"] >= 96 // 16
    # the short request retired BEFORE the long prompt finished prefill
    assert short_done_at is not None and long_prefill_done_at is not None
    assert short_done_at < long_prefill_done_at
    clients = [("long", long_prompt, 4), ("short", short_prompt, 3)]
    want = _sequential_tokens(cfg, spec, params, clients, seed=3)
    for cid in want:
        np.testing.assert_array_equal(eng.results[cid], want[cid])


def test_eviction_under_pool_pressure(lm):
    """Distinct prompts churning through a small pool force LRU
    eviction of cached (refcount-0) prefix blocks; the allocator
    invariants hold and every request still completes exactly."""
    cfg, params, spec = lm
    from ddl_tpu.serve.engine import ServeEngine

    rng = np.random.default_rng(11)
    clients = [
        (f"c{i}",
         rng.integers(0, 256, 20 + 2 * i).astype(np.int32),
         4)
        for i in range(6)
    ]
    eng = ServeEngine(cfg, params, spec, block_size=8, num_blocks=16,
                      max_batch=2)
    for cid, prompt, mn in clients:
        eng.submit(prompt, mn, request_id=cid, rng_seed=3)
    got = eng.run()
    want = _sequential_tokens(cfg, spec, params, clients, seed=3)
    for cid in want:
        np.testing.assert_array_equal(got[cid], want[cid])
    a = eng.allocator
    assert a.evictions > 0  # pressure actually evicted cached blocks
    assert a.used_blocks == 0
    assert a.free_blocks + a.cached_blocks == a.num_blocks
    # index and allocator agree about what is cached
    assert len(eng.prefix) == a.cached_blocks


def test_chunk_bucket_clamped_to_view(lm):
    """Regression (review round 1): a tail whose BUCKET overruns the
    gathered view (17-token tail at off 40 buckets to 32 rows against a
    64-row view: 72 > 64) must shrink the chunk, not let dynamic_slice
    clamp the start and silently read/write the wrong pool rows."""
    cfg, params, spec = lm
    from ddl_tpu.serve.engine import ServeEngine

    rng = np.random.default_rng(21)
    prefix = rng.integers(0, 256, 40).astype(np.int32)  # 5 blocks of 8
    tails = [rng.integers(0, 256, 17).astype(np.int32) for _ in range(2)]
    # DIFFERENT tails: the hit shares exactly the 5 prefix blocks
    # (identical prompts would share 7 full blocks and sidestep the
    # overflowing 32-row tail bucket this test exists to exercise)
    clients = [
        ("owner", np.concatenate([prefix, tails[0]]), 8),
        ("hit", np.concatenate([prefix, tails[1]]), 8),
    ]
    # total = 57 + 8 - 1 = 64 rows -> 8 blocks -> a 64-row view exactly
    eng = ServeEngine(cfg, params, spec, block_size=8, num_blocks=64,
                      max_batch=2, max_blocks_per_seq=8)
    # "hit" shares 5 blocks and prefills from off=40 with 17 remaining:
    # the 32-row bucket would end at 72 > 64 without the clamp
    for cid, p, mn in clients:
        eng.submit(p, mn, request_id=cid, rng_seed=3)
    got = eng.run()
    want = _sequential_tokens(cfg, spec, params, clients, seed=3)
    for cid in want:
        np.testing.assert_array_equal(got[cid], want[cid])
    assert eng.stats["prefix_hits"] == 1
    # the clamp split the tail into two chunks (16 + remainder)
    assert eng.stats["prefill_chunks"] >= 2


def test_trace_sampling_deterministic(lm, tmp_path):
    """DDL_OBS_TRACE_SAMPLE=N emits request spans for 1-in-N requests,
    keyed by submit sequence number — request 0, 2, ... traced, the
    rest silent, and a re-run samples identically."""
    import json

    from ddl_tpu.obs import EventWriter
    from ddl_tpu.obs.events import events_path
    from ddl_tpu.serve.engine import ServeEngine

    cfg, params, spec = lm
    obs = EventWriter(tmp_path, "sampled")
    eng = ServeEngine(cfg, params, spec, block_size=8, num_blocks=64,
                      max_batch=4, obs=obs, trace_sample=2,
                      prefix_cache=False)
    for i in range(4):
        eng.submit(np.arange(1, 7 + i, dtype=np.int32), 3,
                   request_id=f"c{i}", rng_seed=3)
    eng.run()
    obs.close()
    events = [
        json.loads(line)
        for line in events_path(tmp_path, "sampled").read_text().splitlines()
    ]
    roots = sorted(
        e["request_id"] for e in events
        if e["kind"] == "trace_span" and e.get("name") == "request"
    )
    assert roots == ["c0", "c2"]
    # decode latency events are NOT sampled — percentiles see everything
    assert sum(e["kind"] == "decode" for e in events) == 4


def test_serve_bench_scenario_cli(capsys):
    """`serve-bench --scenario shared-prefix --compare-sequential`
    reports the hit rate and exits cleanly on bit-identical tokens."""
    from ddl_tpu.serve import bench

    bench.main([
        "--clients", "6", "--scenario", "shared-prefix",
        "--shared-prefix-len", "16", "--prompt-len", "3:8",
        "--max-new", "6", "--block-size", "8", "--num-blocks", "64",
        "--max-batch", "4", "--compare-sequential", "--seed", "0",
    ])
    out = capsys.readouterr().out
    assert "scenario: shared-prefix" in out
    assert "prefix cache:" in out and "hit rate" in out
    assert "bit-identical to the sequential replay" in out
