"""Pod-level coordinated recovery (ddl_tpu/coord.py + PodSupervisor).

Unit tier: the rendezvous primitives (barrier, stale-peer ageout,
split-brain-free restart-epoch proposal under a real race, rank-0
resume-epoch agreement) and the PodSupervisor protocol driven by
scripted fake children over one tmpdir "NAS".

End-to-end tier: a 3-process pod sim — real tiny-LM trainer children
under real pod supervisors sharing one tmpdir — where an injected
``stall@step`` hang on host 1 makes all three hosts exit and relaunch
in the same restart epoch, restore the same (rank-0-agreed) snapshot,
and reach the same final step and identical final weights, with the
consumed-batch audit proving the resumed stream replayed no batch and
skipped none (the data cursor).
"""

import json
import os
import subprocess
import sys
import threading
import time
from pathlib import Path

import pytest

from ddl_tpu.coord import (
    BarrierTimeout,
    Rendezvous,
    agreed_resume_epoch,
    from_env,
)
from ddl_tpu.supervisor import EXIT_PREEMPTED, EXIT_REJOIN, PodSupervisor
from ddl_tpu.utils.backoff import Backoff

CHILD = Path(__file__).parent / "pod_sim_child.py"


def _rv(root, host, n, **kw):
    kw.setdefault("timeout_s", 10.0)
    kw.setdefault("poll_s", 0.005)
    return Rendezvous(root, host, n, **kw)


# ---------------------------------------------------------------------------
# rendezvous primitives
# ---------------------------------------------------------------------------


def test_barrier_completes_when_all_arrive(tmp_path):
    done = []

    def host(i):
        rv = _rv(tmp_path, i, 3)
        rv.barrier("go")
        done.append(i)

    threads = [threading.Thread(target=host, args=(i,)) for i in range(3)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=10)
    assert sorted(done) == [0, 1, 2]


def test_barrier_times_out_when_a_peer_never_arrives(tmp_path):
    rv = _rv(tmp_path, 0, 2, timeout_s=0.2)
    with pytest.raises(BarrierTimeout, match="1/2 hosts"):
        rv.barrier("lonely")


def test_stale_peer_ageout_only_for_running_hosts(tmp_path):
    a, b, c = _rv(tmp_path, 0, 3), _rv(tmp_path, 1, 3), _rv(tmp_path, 2, 3)
    b.publish_heartbeat("running", 0)
    c.publish_heartbeat("done", 0)
    time.sleep(0.15)
    # b aged out while "running"; c is parked "done" and never stale
    assert a.stale_peers(0.1) == [1]
    assert a.stale_peers(10.0) == []
    b.publish_heartbeat("running", 0)  # a fresh beat clears it
    assert a.stale_peers(0.1) == []


def test_membership_scopes_barriers_peers_and_agreement(tmp_path):
    """Elastic membership: barriers complete over the LIVE member set,
    evicted hosts' heartbeats go invisible, and the agreement leader is
    the lowest surviving id."""
    a = _rv(tmp_path, 0, 3)
    c = _rv(tmp_path, 2, 3)
    # host 1 beat once, then was evicted
    _rv(tmp_path, 1, 3).publish_heartbeat("running", 0)
    a.adopt_membership([0, 2])
    c.adopt_membership([0, 2])
    assert a.world == 2 and a.leader == 0 and a.members == (0, 2)
    time.sleep(0.15)
    assert a.stale_peers(0.1) == []  # the casualty is not re-judged
    # a 2-member barrier completes without host 1
    done = []

    def arrive(rv):
        rv.barrier("shrunk")
        done.append(rv.host)

    t = threading.Thread(target=arrive, args=(c,))
    t.start()
    arrive(a)
    t.join(timeout=10)
    assert sorted(done) == [0, 2]
    assert sorted(a.barrier_arrivals("shrunk")) == [0, 2]
    # eviction is loud: an excluded host cannot adopt the membership
    with pytest.raises(ValueError, match="evicted"):
        _rv(tmp_path, 1, 3).adopt_membership([0, 2])


def test_restart_epoch_proposal_is_split_brain_free(tmp_path):
    """N hosts racing to propose the same restart epoch converge on ONE
    record: one proposer, one cumulative crash count, one agreed
    delay."""
    records = {}

    def propose(i):
        rv = _rv(tmp_path, i, 4)
        records[i] = rv.propose_restart(
            0, reason=f"crash-h{i}", crash=True, preempt=False,
            delay_fn=lambda c: 1.0 + i,  # would differ per host if raced
        )

    threads = [threading.Thread(target=propose, args=(i,)) for i in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=10)
    assert len({r["proposer"] for r in records.values()}) == 1
    assert len({r["delay"] for r in records.values()}) == 1
    for r in records.values():
        assert r["epoch"] == 1
        assert r["crashes"] == 1  # one restart event, counted once
    # the ledger rolls counts forward epoch over epoch
    rv = _rv(tmp_path, 0, 4)
    rec2 = rv.propose_restart(1, "crash", crash=True, preempt=False)
    assert rec2["epoch"] == 2 and rec2["crashes"] == 2


def test_rank0_resume_agreement_overrides_divergent_views(tmp_path, monkeypatch):
    """Torn-NAS shape: hosts compute different latest_valid_epoch; every
    host must restore rank 0's answer."""
    values = {0: 12, 1: 4}  # host 1's local view lags (torn write)
    got = {}

    def host(i):
        rv = _rv(tmp_path, i, 2)
        got[i] = rv.agree("resume-job-e1", lambda: values[i])

    t1 = threading.Thread(target=host, args=(1,))
    t1.start()
    time.sleep(0.05)  # host 1 is already waiting when rank 0 decides
    host(0)
    t1.join(timeout=10)
    assert got == {0: 12, 1: 12}

    # the env-driven wrapper used by checkpoint.resolve_resume
    monkeypatch.setenv("DDL_COORD_DIR", str(tmp_path))
    monkeypatch.setenv("DDL_COORD_HOSTS", "2")
    monkeypatch.setenv("DDL_COORD_HOST", "0")
    monkeypatch.setenv("DDL_RESTART_EPOCH", "2")
    assert from_env().host == 0
    assert agreed_resume_epoch("job", lambda: 7) == 7
    monkeypatch.setenv("DDL_COORD_HOST", "1")
    assert agreed_resume_epoch("job", lambda: 3) == 7  # rank 0's answer
    monkeypatch.delenv("DDL_COORD_DIR")
    assert from_env() is None
    assert agreed_resume_epoch("job", lambda: 5) == 5  # non-pod fallback


def test_abort_is_pod_wide_and_first_writer_wins(tmp_path):
    a, b = _rv(tmp_path, 0, 2), _rv(tmp_path, 1, 2)
    rec = a.abort("crash budget exhausted", 9)
    assert b.aborted()["rc"] == 9
    # a later abort keeps the original story
    assert b.abort("something else", 3)["reason"] == "crash budget exhausted"
    assert rec["host"] == 0


# ---------------------------------------------------------------------------
# PodSupervisor protocol (scripted fake children, threads as hosts)
# ---------------------------------------------------------------------------


class FakeChild:
    """Scripted child: exits ``rc`` after ``delay`` seconds, or hangs
    forever (rc=None) until terminated."""

    def __init__(self, rc=None, delay=0.05):
        self.rc = rc
        self.delay = delay
        self.t0 = time.monotonic()
        self.killed = False

    def poll(self):
        if self.killed:
            return -15
        if self.rc is None:
            return None
        return self.rc if time.monotonic() - self.t0 >= self.delay else None

    def terminate(self):
        self.killed = True

    kill = terminate

    def wait(self, timeout=None):
        return self.poll()


def _run_pod(tmp_path, scripts, n_hosts=None, events=None, **sup_kwargs):
    """Run one PodSupervisor per host in threads; ``scripts[i]`` is the
    list of children host i spawns, in order.  Returns {host: exit}."""
    n_hosts = n_hosts if n_hosts is not None else len(scripts)
    sup_kwargs.setdefault("backoff", Backoff(base=0.01, jitter=0.0))
    results = {}
    sups = {}

    def host(i):
        rv = _rv(tmp_path, i, n_hosts)
        it = iter(scripts[i])
        sup = PodSupervisor(
            lambda epoch, idx: next(it), rv,
            poll_s=0.005, heartbeat_s=0.02, stale_after_s=30.0,
            log=lambda m: None,
            events=(events or {}).get(i),
            **sup_kwargs,
        )
        sups[i] = sup
        results[i] = sup.run()

    threads = [
        threading.Thread(target=host, args=(i,)) for i in range(len(scripts))
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=30)
    assert not any(t.is_alive() for t in threads), "pod deadlocked"
    return results


def test_pod_completes_when_all_children_succeed(tmp_path):
    results = _run_pod(tmp_path, [[FakeChild(rc=0)], [FakeChild(rc=0)]])
    assert results == {0: 0, 1: 0}
    assert _rv(tmp_path, 0, 2).current_epoch() == 0  # no restart proposed


def test_one_crash_restarts_every_host_in_the_same_epoch(tmp_path):
    """Host 1 crashes; host 0's healthy child (hanging mid-'collective')
    is killed and both hosts relaunch together in restart epoch 1."""
    h0 = [FakeChild(rc=None), FakeChild(rc=0)]
    results = _run_pod(tmp_path, [h0, [FakeChild(rc=1), FakeChild(rc=0)]])
    assert results == {0: 0, 1: 0}
    assert h0[0].killed  # the healthy child was killed, not abandoned
    rv = _rv(tmp_path, 0, 2)
    assert rv.current_epoch() == 1
    rec = rv.epoch_record(1)
    assert rec["crashes"] == 1 and rec["reason"].endswith("crash")


def test_completed_host_rejoins_a_restart_proposed_while_it_waits(tmp_path):
    """Host 0 finishes its run; host 1 then crashes.  Host 0 must leave
    the done barrier and retrain — the resumed collective needs every
    host."""
    h0 = [FakeChild(rc=0, delay=0.01), FakeChild(rc=0)]
    h1 = [FakeChild(rc=1, delay=0.3), FakeChild(rc=0)]
    results = _run_pod(tmp_path, [h0, h1])
    assert results == {0: 0, 1: 0}
    assert _rv(tmp_path, 0, 2).current_epoch() == 1


def test_resumable_exits_do_not_consume_the_crash_budget(tmp_path):
    h0 = [FakeChild(rc=EXIT_PREEMPTED, delay=0.01), FakeChild(rc=0)]
    h1 = [FakeChild(rc=None), FakeChild(rc=0)]
    results = _run_pod(tmp_path, [h0, h1], max_restarts=0)
    assert results == {0: 0, 1: 0}  # survives despite a zero crash budget
    rec = _rv(tmp_path, 0, 2).epoch_record(1)
    assert rec["crashes"] == 0 and rec["preemptions"] == 1
    assert rec["delay"] == 0.0  # preemptions relaunch without backoff


def test_crash_budget_exhaustion_aborts_the_whole_pod(tmp_path):
    h0 = [FakeChild(rc=None), FakeChild(rc=None)]
    h1 = [FakeChild(rc=7, delay=0.01), FakeChild(rc=7, delay=0.01)]
    results = _run_pod(tmp_path, [h0, h1], max_restarts=1)
    # both hosts exit with the crashing host's code, not just the crasher
    assert results == {0: 7, 1: 7}
    ab = _rv(tmp_path, 0, 2).aborted()
    assert ab is not None and "crash budget" in ab["reason"]


def test_stale_peer_triggers_escalation_not_eternal_hang(tmp_path):
    """Host 1's supervisor dies silently (no heartbeat, child hangs).
    Host 0 must detect the aged-out heartbeat, attempt a coordinated
    restart, and — when the dead peer never joins the barrier — abort
    rather than hang forever."""
    rv1 = _rv(tmp_path, 1, 2)
    rv1.arrive("start")  # host 1 made the start barrier...
    rv1.publish_heartbeat("running", 0)  # ...beat once, then died

    rv0 = _rv(tmp_path, 0, 2, timeout_s=0.5)
    child = FakeChild(rc=None)
    sup = PodSupervisor(
        lambda epoch, idx: child, rv0,
        poll_s=0.005, heartbeat_s=0.02, stale_after_s=0.1,
        backoff=Backoff(base=0.01, jitter=0.0), log=lambda m: None,
    )
    rc = sup.run()
    assert rc != 0
    assert child.killed
    ab = rv0.aborted()
    assert ab is not None and "join" in ab["reason"]


def test_pod_supervisor_emits_coordination_events(tmp_path):
    from ddl_tpu.obs import EventWriter, read_events

    w0 = EventWriter(tmp_path / "logs", "podjob", host=0)
    results = _run_pod(
        tmp_path / "nas",
        [[FakeChild(rc=None), FakeChild(rc=0)],
         [FakeChild(rc=1, delay=0.01), FakeChild(rc=0)]],
        events={0: w0},
    )
    assert results == {0: 0, 1: 0}
    w0.close()
    events = read_events(w0.path)
    kinds = [e["kind"] for e in events]
    assert kinds[0] == "supervisor_start"
    assert "coord_barrier" in kinds and "pod_restart" in kinds
    restart = next(e for e in events if e["kind"] == "pod_restart")
    # either host may win the proposal race; the classification must
    # still be the crash (reason "crash" from the crasher itself or
    # "peer_crash" from the bystander that saw its intent)
    assert restart["epoch"] == 1 and restart["reason"].endswith("crash")
    assert kinds[-1] == "supervisor_done"


# ---------------------------------------------------------------------------
# elastic mode: continue on N-1 (scripted fake children)
# ---------------------------------------------------------------------------


def test_elastic_stale_peer_evicted_after_grace_and_pod_continues(tmp_path):
    """Host 1's supervisor dies permanently right after the start
    barrier.  The elastic survivors hold the eviction grace, then agree
    restart epoch 1 with membership [0, 2] / world 2 — and finish as a
    2-host pod instead of aborting."""
    rv1 = _rv(tmp_path, 1, 3)
    rv1.arrive("start")
    rv1.publish_heartbeat("running", 0)  # beat once, then silence

    scripts = {0: [FakeChild(rc=None), FakeChild(rc=0)],
               2: [FakeChild(rc=None), FakeChild(rc=0)]}
    results = {}

    def host(i):
        rv = _rv(tmp_path, i, 3)
        it = iter(scripts[i])
        sup = PodSupervisor(
            lambda epoch, idx: next(it), rv,
            poll_s=0.005, heartbeat_s=0.02,
            stale_after_s=0.15, elastic=True, elastic_grace_s=0.2,
            backoff=Backoff(base=0.01, jitter=0.0), log=lambda m: None,
        )
        results[i] = sup.run()

    threads = [threading.Thread(target=host, args=(i,)) for i in (0, 2)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=30)
    assert not any(t.is_alive() for t in threads), "elastic pod deadlocked"
    assert results == {0: 0, 2: 0}
    # the epoch-0 children (hung in the dead host's collective) were
    # killed, not abandoned
    assert scripts[0][0].killed and scripts[2][0].killed
    rv = _rv(tmp_path, 0, 3)
    assert rv.aborted() is None
    assert rv.current_epoch() == 1
    rec = rv.epoch_record(1)
    assert rec["reason"] == "peer_lost"
    assert rec["hosts"] == [0, 2] and rec["world"] == 2
    # an eviction is a preemption-class event, never a crash
    assert rec["crashes"] == 0 and rec["preemptions"] == 1


def test_elastic_join_barrier_timeout_scales_down_to_arrivals(tmp_path):
    """The second eviction route: a peer whose child crashed and whose
    supervisor then died never reaches the join barrier.  The arrived
    host proposes the NEXT epoch over the arrivals and continues
    alone."""
    rv1 = _rv(tmp_path, 1, 2)
    rv1.arrive("start")
    rv1.publish_heartbeat("running", 0)
    rv1.publish_intent("crash", 1, 0)  # child died; supervisor died too

    child0, child1 = FakeChild(rc=None), FakeChild(rc=0)
    it = iter([child0, child1])
    rv0 = _rv(tmp_path, 0, 2, timeout_s=0.4)
    sup = PodSupervisor(
        lambda epoch, idx: next(it), rv0,
        poll_s=0.005, heartbeat_s=0.02, stale_after_s=30.0,
        elastic=True,
        backoff=Backoff(base=0.01, jitter=0.0), log=lambda m: None,
    )
    assert sup.run() == 0
    assert child0.killed
    assert rv0.aborted() is None
    # epoch 1 = the crash restart (full membership, budget consumed);
    # epoch 2 = the join-timeout eviction (membership [0])
    assert rv0.current_epoch() == 2
    rec1, rec2 = rv0.epoch_record(1), rv0.epoch_record(2)
    assert rec1["crashes"] == 1
    assert rec2["reason"] == "peer_lost"
    assert rec2["hosts"] == [0] and rec2["world"] == 1
    assert rec2["crashes"] == 1  # rolled forward, not re-counted


def test_evicted_host_exits_cleanly_instead_of_aborting(tmp_path):
    """A live-but-slow host that catches up after the survivors already
    scaled down must exit 0 (evicted), never abort the pod out from
    under them."""
    from ddl_tpu.obs import EventWriter, read_events

    w1 = EventWriter(tmp_path / "logs", "evictjob", host=1)
    scripts = {
        0: [FakeChild(rc=1, delay=0.05), FakeChild(rc=0)],
        1: [FakeChild(rc=None), FakeChild(rc=None)],
    }
    results = {}

    def host(i):
        rv = _rv(
            tmp_path / "nas", i, 2,
            timeout_s=(0.4 if i == 0 else 10.0),
        )
        it = iter(scripts[i])
        sup = PodSupervisor(
            lambda epoch, idx: next(it), rv,
            poll_s=0.005, heartbeat_s=0.02, stale_after_s=30.0,
            # host 1 keeps heartbeating but is slow to see signals, so
            # it misses host 0's join barrier (the barrier route, not
            # the staleness route)
            signal_poll_s=(0.05 if i == 0 else 1.5),
            elastic=True,
            backoff=Backoff(base=0.01, jitter=0.0), log=lambda m: None,
            events=(w1 if i == 1 else None),
        )
        results[i] = sup.run()

    threads = [threading.Thread(target=host, args=(i,)) for i in (0, 1)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=30)
    assert not any(t.is_alive() for t in threads), "evict sim deadlocked"
    # BOTH exit 0: host 0 finished the run alone, host 1 was evicted
    assert results == {0: 0, 1: 0}
    w1.close()
    rv = _rv(tmp_path / "nas", 0, 2)
    assert rv.aborted() is None
    final = rv.epoch_record(rv.current_epoch())
    assert final["hosts"] == [0]
    done = [e for e in read_events(w1.path)
            if e["kind"] == "supervisor_done"]
    assert done and done[-1]["rc"] == 0 and done[-1].get("evicted") is True


def test_join_request_intake_filters_members_and_stale(tmp_path):
    """The leader's view of ``joins/``: a non-member's marker surfaces
    with an age, a member's own leftover marker is void, out-of-range
    hosts are ignored, and stale markers (a joiner that died mid-wait)
    are dropped under ``fresh_s``."""
    rv0 = _rv(tmp_path, 0, 3)
    rv0.adopt_membership([0, 2])  # host 1 was evicted earlier
    rv1 = _rv(tmp_path, 1, 3)
    assert rv0.join_requests() == []
    rv1.publish_join_request(1, note="back")
    (req,) = rv0.join_requests()
    assert req["host"] == 1 and req["epoch"] == 1
    assert req["age"] >= 0.0 and req["note"] == "back"
    # a member's leftover marker is void by definition
    rv2 = _rv(tmp_path, 2, 3)
    rv2.adopt_membership([0, 2])
    rv2.publish_join_request(1)
    assert [r["host"] for r in rv0.join_requests()] == [1]
    # a host outside this launch's [0, n_hosts) is ignored
    (tmp_path / "joins" / "h099.json").write_text(
        json.dumps({"ts": rv0.clock(), "host": 99, "epoch": 0})
    )
    assert [r["host"] for r in rv0.join_requests()] == [1]
    # a stale marker means the joiner went silent after asking
    (tmp_path / "joins" / "h001.json").write_text(
        json.dumps({"ts": rv0.clock() - 60.0, "host": 1, "epoch": 1})
    )
    assert rv0.join_requests(fresh_s=5.0) == []
    assert [r["host"] for r in rv0.join_requests()] == [1]  # unbounded
    # refreshing the marker (the joiner's heartbeat analogue) revives it
    rv1.publish_join_request(2)
    assert [r["host"] for r in rv0.join_requests(fresh_s=5.0)] == [1]
    rv1.clear_join_request()
    assert rv0.join_requests() == []


def test_grow_epoch_ledger_rides_first_writer_wins(tmp_path):
    """A grow proposal is the same atomically-created ledger record as
    a shrink: budgets roll forward unchanged, the record carries the
    LARGER host set, and a racing proposer adopts the winner."""
    rv = _rv(tmp_path, 0, 3)
    rec1 = rv.propose_restart(
        0, "peer_lost", crash=False, preempt=True, rc=EXIT_PREEMPTED,
        hosts=[0, 2],
    )
    rv.adopt_membership(rec1["hosts"])
    assert rv.world == 2
    rec2 = rv.propose_restart(
        1, "peer_join", crash=False, preempt=False, rc=EXIT_PREEMPTED,
        hosts=[0, 1, 2],
    )
    assert rec2["hosts"] == [0, 1, 2] and rec2["world"] == 3
    # a grow is neither a crash nor a preemption; budgets roll forward
    assert rec2["crashes"] == 0 and rec2["preemptions"] == 1
    assert rec2["delay"] == 0.0  # growth relaunches without backoff
    # a racing proposer still on the shrunken membership loses the race
    # and adopts the grown record unchanged (one restart event, one
    # classification — even when the racers disagreed on the reason)
    rv2 = _rv(tmp_path, 2, 3)
    rv2.adopt_membership([0, 2])
    won = rv2.propose_restart(
        1, "peer_stale/crash", crash=True, preempt=False,
        delay_fn=lambda n: 9.9,
    )
    assert won == rec2
    rv2.adopt_membership(won["hosts"])
    assert rv2.world == 3 and rv2.leader == 0


def test_elastic_rejoin_child_leaves_and_is_grown_back(tmp_path):
    """The full scripted grow cycle: host 1's child exits EXIT_REJOIN
    (a voluntary leave, e.g. an injected ``rejoin`` fault), the pod
    shrinks to [0], host 1's supervisor publishes a join_request from
    ``_await_rejoin``, and the leader answers with a ``peer_join``
    epoch whose membership is [0, 1] again.  Both hosts finish at the
    grown world; no budget was burned at any step."""
    scripts = {
        # epoch-0 child killed at the rejoin intent; epoch-1 child
        # (world [0]) killed at the peer_join; epoch-2 child completes
        0: [FakeChild(rc=None), FakeChild(rc=None), FakeChild(rc=0)],
        # epoch-0 child leaves voluntarily; host 1 is not a member of
        # epoch 1, so its next child runs in epoch 2
        1: [FakeChild(rc=EXIT_REJOIN, delay=0.05), FakeChild(rc=0)],
    }
    results = _run_pod(
        tmp_path, [scripts[0], scripts[1]], elastic=True, max_restarts=0,
    )
    assert results == {0: 0, 1: 0}
    assert scripts[0][0].killed and scripts[0][1].killed
    rv = _rv(tmp_path, 0, 2)
    assert rv.aborted() is None
    assert rv.current_epoch() == 2
    rec1, rec2 = rv.epoch_record(1), rv.epoch_record(2)
    assert rec1["reason"] in ("rejoin", "peer_rejoin")
    assert rec1["hosts"] == [0] and rec1["world"] == 1
    assert rec1["rc"] == EXIT_REJOIN
    assert rec1["crashes"] == 0 and rec1["preemptions"] == 0
    assert rec2["reason"] == "peer_join"
    assert rec2["hosts"] == [0, 1] and rec2["world"] == 2
    assert rec2["crashes"] == 0 and rec2["preemptions"] == 0
    # the joiner withdrew its marker once the grow epoch admitted it
    assert rv.join_requests() == []


# ---------------------------------------------------------------------------
# end-to-end: the 3-host pod sim (real trainers, real supervisors)
# ---------------------------------------------------------------------------


def _clean_env() -> dict:
    """The suite's environment minus everything that would leak pod/
    fault/coordination state into a sim's children."""
    return {
        k: v for k, v in os.environ.items()
        if k not in ("XLA_FLAGS", "JAX_PLATFORMS", "DDL_FAULT",
                     "DDL_FAULT_STATE", "DDL_WATCHDOG_S", "DDL_COORD_DIR",
                     "DDL_COORD_HOSTS", "DDL_COORD_HOST", "DDL_HOST_ID",
                     "DDL_RESTART_EPOCH", "DDL_SUPERVISED",
                     "DDL_OBS_STEP_SPANS", "DDL_COORD_MEMBERS",
                     "DDL_NUM_PROCESSES", "DDL_PROCESS_ID",
                     "DDL_LAUNCH_TOKEN", "DDL_COMPILE_CACHE")
    }


def _read_consumed(sim: Path, host: int) -> list[tuple[int, int]]:
    out = []
    for line in (sim / f"consumed_h{host}.log").read_text().splitlines():
        e, s = line.split()
        out.append((int(e), int(s)))
    return out


def _warm_compile_cache(sim_env: dict, tmp_path: Path) -> None:
    """One plain 1-step child run to seed the persistent XLA cache, so
    generation-0 children compile in far less than the watchdog
    deadline."""
    env = dict(sim_env, DDL_SIM_DIR=str(tmp_path / "warmup"),
               DDL_SIM_STEPS="1", DDL_SIM_PACE="0")
    (tmp_path / "warmup").mkdir()
    subprocess.run(
        [sys.executable, str(CHILD)], env=env, check=True, timeout=240,
        stdout=subprocess.DEVNULL, stderr=subprocess.STDOUT,
    )


def test_three_host_pod_sim_stall_escalation_and_exact_resume(tmp_path):
    """The acceptance scenario end to end: stall@step on host 1 → its
    watchdog escalates (exit-intent + resumable exit) → ALL THREE hosts
    kill their trainers and relaunch in the same restart epoch → every
    host restores the rank-0-agreed snapshot → identical final step and
    identical final weights on every host, and each host's final
    incarnation consumed exactly the batches from the restored cursor to
    the end — none duplicated, none skipped."""
    from ddl_tpu import checkpoint as ckpt
    from ddl_tpu.supervisor import supervise_pod_command

    sim = tmp_path / "sim"
    nas = tmp_path / "nas"
    sim.mkdir()
    nas.mkdir()
    steps = 10
    base_env = _clean_env()
    base_env.update(
        DDL_SIM_DIR=str(sim),
        DDL_SIM_STEPS=str(steps),
        DDL_SIM_PACE="0.8",
        DDL_JOB_ID="podsim",
        DDL_LOG_DIR=str(sim / "suplogs"),
        DDL_WATCHDOG_S="4",
        DDL_TEST_COMPILE_CACHE=os.environ.get(
            "DDL_TEST_COMPILE_CACHE", "/tmp/ddl_tpu_test_xla_cache"
        ),
    )
    _warm_compile_cache(base_env, tmp_path)

    results = {}

    def host(i):
        env = dict(base_env)
        if i == 1:
            # stall EARLY so the coordinated kill lands mid-run on the
            # healthy hosts (a late kill can let a graceful SIGTERM
            # snapshot complete the whole run — also legal, but the
            # interesting audit is a nonempty resume tail)
            env["DDL_FAULT"] = "stall@step:2:300"  # the hang
        results[i] = supervise_pod_command(
            [sys.executable, str(CHILD)], nas, i, 3,
            env=env, max_restarts=3,
            backoff=Backoff(base=0.01, jitter=0.0),
            poll_s=0.05, heartbeat_s=0.2, stale_after_s=60.0,
            log=lambda m: None,
        )

    threads = [threading.Thread(target=host, args=(i,)) for i in range(3)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=300)
    assert not any(t.is_alive() for t in threads), "pod sim deadlocked"
    assert results == {0: 0, 1: 0, 2: 0}, results

    # the rendezvous state is run-scoped: markers live under the
    # launch-token subdir acquire_launch opened for this pod lifetime
    from ddl_tpu.coord import active_launch_root

    launch = active_launch_root(nas)
    assert launch is not None and launch.parent == nas / "launches"
    # the completed launch is closed, so a lone relaunched host cannot
    # rejoin its barriers (it would open a fresh subdir instead)
    assert (launch / "finished.json").is_file()
    rv = _rv(launch, 0, 3)
    # exactly one coordinated restart, triggered by the stalled host
    assert rv.current_epoch() == 1, rv.current_epoch()
    rec = rv.epoch_record(1)
    assert rec["crashes"] == 0  # a hang is resumable, not a crash

    # every host completed IN RESTART EPOCH 1, at the same final step,
    # with bit-identical weights
    finals = []
    for i in range(3):
        last = (sim / f"final_h{i}.log").read_text().splitlines()[-1]
        e, step, digest = last.split()
        finals.append((int(e), int(step), digest))
    assert all(e == 1 for e, _, _ in finals), finals
    assert all(s == steps for _, s, _ in finals), finals
    assert len({d for _, _, d in finals}) == 1, finals

    # exact resume: host 0 published the agreed snapshot through the
    # rendezvous (read the marker directly — rank 0's agree() would
    # recompute); its manifest cursor is the resume step, and every
    # host's final incarnation consumed exactly [cursor .. steps)
    import json

    agreed = json.loads(
        (launch / "agree" / "resume-podsim-e1.json").read_text()
    )["value"]
    # agreed None is a legal race: the coordinated kill can land before
    # any snapshot COMMITTED (the stall fires at step 2; under suite
    # load the healthy hosts may be killed mid-first-save, which
    # integrity checking rightly refuses) — rank 0 then agrees on "no
    # snapshot" and every host retrains from scratch, which the audit
    # below still proves batch-exact
    if agreed is not None:
        cursor = ckpt.read_cursor(sim / "ckpt", "podsim", agreed)
        assert cursor is not None and cursor["step"] == agreed
    resume_from = 0 if agreed is None else agreed
    for i in range(3):
        # the epoch-1 incarnation consumed exactly [resume_from, steps)
        # — empty iff the agreed snapshot already held the completed run
        # (a graceful coordinated-kill snapshot landed at the last step)
        tail = [s for e, s in _read_consumed(sim, i) if e == 1]
        assert tail == list(range(resume_from, steps)), (
            f"h{i} replayed or skipped batches: {tail} "
            f"(agreed resume {agreed})"
        )

    # the live-monitoring surfaces read the pod's shared supervisor
    # stream dir (three per-host files with barrier completion stamps):
    # watch renders a populated frame, export scrapes per-host series
    # including the barrier-fit clock offsets over the shared barriers
    from ddl_tpu.obs.export import prometheus_text
    from ddl_tpu.obs.fold import fold_job
    from ddl_tpu.obs.watch import build_frame

    fold = fold_job(sim / "suplogs", "podsim", cache=False)
    assert len(fold.streams) == 3
    frame = build_frame(fold, "podsim")
    assert "pod_restart" in frame
    assert "clk_off_s" in frame
    scrape = prometheus_text(fold, "podsim")
    assert "ddl_obs_barrier_wait_seconds_total{" in scrape
    assert "ddl_obs_clock_offset_seconds{" in scrape
    assert scrape.count('ddl_obs_restarts_total{') == 3

    # restart-latency accounting (obs): every relaunched child that
    # trained in epoch 1 stamped its first completed step against the
    # pod-wide restart decision (DDL_RELAUNCH_TS from the epoch
    # record's proposal time) — the relaunch-to-step metric
    from ddl_tpu.obs.events import read_events

    for i in range(3):
        if not [s for e, s in _read_consumed(sim, i) if e == 1]:
            continue  # trained nothing in epoch 1: no first step to stamp
        evs = read_events(
            sim / f"logs_h{i}" / "by_job_id" / "podsim"
            / f"events-h{i:03d}.jsonl"
        )
        rls = [e for e in evs if e.get("kind") == "restart_latency"]
        assert rls, f"h{i} emitted no restart_latency event"
        assert rls[-1].get("repoch") == 1, rls[-1]
        assert rls[-1]["latency"] > 0
        # the decision origin is the epoch record's proposal stamp
        assert rls[-1]["decision_ts"] == pytest.approx(rec["ts"])

    # goodput ledger (round 20) on the real pod-sim streams: every
    # (host, repoch) incarnation's buckets sum EXACTLY to its wall
    # clock, the epoch-1 incarnation's wall starts at the pod-wide
    # restart decision (booking the relaunch gap as restart_gap +
    # barrier), the resumed child's snapshot restore landed in the
    # checkpoint bucket, and warm == cold through the sidecar
    from ddl_tpu.obs.goodput import ledger_from_fold, render_goodput

    for i in range(3):
        logs = sim / f"logs_h{i}"
        f_cold = fold_job(logs, "podsim", cache=False)
        ledger = ledger_from_fold(f_cold)
        assert ledger["incarnations"], f"h{i}: empty goodput ledger"
        for inc in ledger["incarnations"]:
            total = sum(inc["seconds"].values())
            assert total == pytest.approx(inc["wall_s"], abs=1e-9)
            # attribution never meaningfully exceeds the wall (the
            # acceptance's 1% bound on the residual)
            assert inc["seconds"]["untracked"] >= -0.01 * max(
                inc["wall_s"], 1e-9
            ), (i, inc)
        e1 = [a for a in ledger["incarnations"] if a["repoch"] == 1]
        trained_e1 = [s for e, s in _read_consumed(sim, i) if e == 1]
        if e1 and trained_e1:
            acc = e1[0]
            # the decision-anchored window books the relaunch cost
            assert (
                acc["seconds"]["restart_gap"] + acc["seconds"]["barrier"]
            ) > 0, acc
            if agreed is not None:
                assert acc["seconds"]["checkpoint"] > 0, acc
        warm = render_goodput(
            ledger_from_fold(fold_job(logs, "podsim", cache=True)),
            "podsim",
        )
        assert warm == render_goodput(ledger, "podsim")


def test_three_host_pod_sim_permanent_host_loss_elastic_continue(tmp_path):
    """The elastic acceptance e2e: host 1's supervisor makes the start
    barrier, heartbeats once, and dies PERMANENTLY before launching its
    trainer.  The two elastic survivors hold the eviction grace, agree
    restart epoch 1 with membership [0, 2] / world 2 through the epoch
    ledger, relaunch with the respecced bootstrap env
    (``DDL_COORD_MEMBERS=0,2``, survivors renumbered contiguously),
    resume the rank-0-agreed snapshot, and finish with identical final
    weights — the epoch-1 tail consuming exactly [resume, steps) on
    both survivors (no batch lost to the eviction, none replayed)."""
    import json

    from ddl_tpu import checkpoint as ckpt
    from ddl_tpu import coord
    from ddl_tpu.supervisor import supervise_pod_command

    sim = tmp_path / "sim"
    nas = tmp_path / "nas"
    sim.mkdir()
    nas.mkdir()
    steps = 8
    base_env = _clean_env()
    base_env.update(
        DDL_SIM_DIR=str(sim),
        DDL_SIM_STEPS=str(steps),
        DDL_SIM_PACE="0.5",
        DDL_JOB_ID="podelastic",
        DDL_LOG_DIR=str(sim / "suplogs"),
        DDL_WATCHDOG_S="30",
        DDL_TEST_COMPILE_CACHE=os.environ.get(
            "DDL_TEST_COMPILE_CACHE", "/tmp/ddl_tpu_test_xla_cache"
        ),
    )
    _warm_compile_cache(base_env, tmp_path)

    # host 1: the supervisor joins the pod's launch, arrives at the
    # start barrier, beats once as "running" — then dies outright (it
    # never spawns a child and never beats again)
    launch1 = coord.acquire_launch(nas)
    rv1 = Rendezvous(launch1, 1, 3)
    rv1.arrive("start")
    rv1.publish_heartbeat("running", 0)

    results = {}

    def host(i):
        results[i] = supervise_pod_command(
            [sys.executable, str(CHILD)], nas, i, 3,
            env=dict(base_env), max_restarts=3,
            backoff=Backoff(base=0.01, jitter=0.0),
            poll_s=0.05, heartbeat_s=0.2, stale_after_s=1.5,
            elastic=True, elastic_grace_s=1.5,
            log=lambda m: None,
        )

    threads = [threading.Thread(target=host, args=(i,)) for i in (0, 2)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=300)
    assert not any(t.is_alive() for t in threads), "elastic sim deadlocked"
    assert results == {0: 0, 2: 0}, results

    # all three joined ONE launch; the survivors closed it
    launch = coord.active_launch_root(nas)
    assert launch == launch1
    assert (launch / "finished.json").is_file()
    rv = _rv(launch, 0, 3)
    assert rv.aborted() is None
    assert rv.current_epoch() == 1, rv.current_epoch()
    rec = rv.epoch_record(1)
    assert rec["reason"] == "peer_lost"
    assert rec["hosts"] == [0, 2] and rec["world"] == 2
    assert rec["crashes"] == 0  # losing a host is not a crash

    # both survivors finished IN EPOCH 1, same step, identical weights;
    # the dead host never trained at all
    finals = {}
    for i in (0, 2):
        last = (sim / f"final_h{i}.log").read_text().splitlines()[-1]
        e, step, digest = last.split()
        finals[i] = (int(e), int(step), digest)
    assert all(f == (1, steps, finals[0][2]) for f in finals.values()), finals
    assert not (sim / "final_h1.log").exists()

    # the relaunch env carried the agreed membership and the
    # contiguously-renumbered SPMD bootstrap (the data-axis respec the
    # children's `parallel/rules` world derivation reads)
    for i in (0, 2):
        lines = (sim / f"env_h{i}.log").read_text().splitlines()
        e1 = [ln for ln in lines if ln.startswith("1 ")][-1]
        assert "members=0,2" in e1, e1
        assert "nproc=2" in e1, e1
        assert f"pid={0 if i == 0 else 1}" in e1, e1
        e0 = [ln for ln in lines if ln.startswith("0 ")][0]
        assert "members=0,1,2" in e0 and "nproc=-" in e0, e0

    # exact resume over the agreed snapshot: the epoch-1 incarnations
    # consumed exactly [resume, steps) — agreed None is the legal
    # killed-before-first-commit race (retrain from scratch, still
    # batch-exact)
    agreed = json.loads(
        (launch / "agree" / "resume-podelastic-e1.json").read_text()
    )["value"]
    if agreed is not None:
        cursor = ckpt.read_cursor(sim / "ckpt", "podelastic", agreed)
        assert cursor is not None and cursor["step"] == agreed
    resume_from = 0 if agreed is None else agreed
    for i in (0, 2):
        tail = [s for e, s in _read_consumed(sim, i) if e == 1]
        assert tail == list(range(resume_from, steps)), (
            f"h{i} replayed or skipped batches: {tail} "
            f"(agreed resume {agreed})"
        )


def test_three_host_pod_sim_host_loss_then_rejoin(tmp_path):
    """The elastic scale-UP acceptance e2e, the full churn cycle on
    real trainers: host 1's supervisor dies permanently after the
    start barrier, the survivors evict it and train ON at world 2
    ([0, 2], renumbered) — then a replacement host-1 supervisor starts
    into the shrunken launch, fails membership adoption, publishes a
    join_request, and the leader answers with a ``peer_join`` restart
    epoch at the FULL membership.  All three hosts finish epoch 2 with
    identical final weights: the ZeRO-sharded state crossed dp layouts
    twice (shrink at e1, grow at e2) through the ordinary
    rank-0-agreed restore, and every epoch's consumed tail runs
    exactly [agreed resume, ...) — no batch lost to the churn, none
    replayed within a lineage."""
    from ddl_tpu import checkpoint as ckpt
    from ddl_tpu import coord
    from ddl_tpu.supervisor import supervise_pod_command

    sim = tmp_path / "sim"
    nas = tmp_path / "nas"
    sim.mkdir()
    nas.mkdir()
    steps = 12
    base_env = _clean_env()
    base_env.update(
        DDL_SIM_DIR=str(sim),
        DDL_SIM_STEPS=str(steps),
        DDL_SIM_PACE="0.35",
        DDL_JOB_ID="podrejoin",
        DDL_LOG_DIR=str(sim / "suplogs"),
        DDL_WATCHDOG_S="30",
        DDL_TEST_COMPILE_CACHE=os.environ.get(
            "DDL_TEST_COMPILE_CACHE", "/tmp/ddl_tpu_test_xla_cache"
        ),
    )
    _warm_compile_cache(base_env, tmp_path)

    # host 1 makes the start barrier, beats once as "running" — then
    # its supervisor dies outright (the same loss the elastic-continue
    # e2e pins; this test carries the story through the grow)
    launch1 = coord.acquire_launch(nas)
    rv1 = Rendezvous(launch1, 1, 3)
    rv1.arrive("start")
    rv1.publish_heartbeat("running", 0)

    results = {}

    def host(i):
        results[i] = supervise_pod_command(
            [sys.executable, str(CHILD)], nas, i, 3,
            env=dict(base_env), max_restarts=3,
            backoff=Backoff(base=0.01, jitter=0.0),
            poll_s=0.05, heartbeat_s=0.2, stale_after_s=1.5,
            elastic=True, elastic_grace_s=1.5,
            log=lambda m: None,
        )

    threads = {i: threading.Thread(target=host, args=(i,)) for i in (0, 2)}
    for t in threads.values():
        t.start()

    # the replacement host-1 supervisor starts only once the world-2
    # incarnation has actually TRAINED a batch — the rejoin must
    # interrupt a live shrunken pod mid-run, not race the eviction
    # boundary (an immediate re-grow is legal but would leave the
    # world-2 epoch this test audits without a single step)
    deadline = time.monotonic() + 120
    while time.monotonic() < deadline:
        try:
            if any(e == 1 for e, _ in _read_consumed(sim, 0)):
                break
        except OSError:
            pass
        time.sleep(0.05)
    else:
        pytest.fail("survivors never trained at world 2")

    threads[1] = threading.Thread(target=host, args=(1,))
    threads[1].start()
    for t in threads.values():
        t.join(timeout=300)
    assert not any(
        t.is_alive() for t in threads.values()
    ), "rejoin sim deadlocked"
    assert results == {0: 0, 1: 0, 2: 0}, results

    launch = coord.active_launch_root(nas)
    assert launch == launch1 and (launch / "finished.json").is_file()
    rv = _rv(launch, 0, 3)
    assert rv.aborted() is None
    assert rv.current_epoch() == 2, rv.current_epoch()
    rec1, rec2 = rv.epoch_record(1), rv.epoch_record(2)
    # epoch 1: the eviction (a preemption-class event, never a crash)
    assert rec1["reason"] == "peer_lost", rec1
    assert rec1["hosts"] == [0, 2] and rec1["world"] == 2
    assert rec1["crashes"] == 0 and rec1["preemptions"] == 1
    # epoch 2: the grow — proposed by the leader off the join_request,
    # burning NO budget of either class
    assert rec2["reason"] == "peer_join", rec2
    assert rec2["hosts"] == [0, 1, 2] and rec2["world"] == 3
    assert rec2["crashes"] == 0 and rec2["preemptions"] == 1
    assert rv.join_requests() == []  # marker withdrawn on admission

    # ALL THREE hosts finished IN EPOCH 2, same step, identical weights
    finals = {}
    for i in range(3):
        last = (sim / f"final_h{i}.log").read_text().splitlines()[-1]
        e, step, digest = last.split()
        finals[i] = (int(e), int(step), digest)
    assert all(
        f == (2, steps, finals[0][2]) for f in finals.values()
    ), finals

    # env audit: epoch 1 ran the renumbered 2-host world on the
    # survivors; epoch 2 dropped the override (back to the full world)
    # on everyone.  Host 1's ONLY incarnation is the epoch-2 one.
    for i in (0, 2):
        lines = (sim / f"env_h{i}.log").read_text().splitlines()
        e1 = [ln for ln in lines if ln.startswith("1 ")][-1]
        assert "members=0,2" in e1 and "nproc=2" in e1, e1
        assert f"pid={0 if i == 0 else 1}" in e1, e1
    lines1 = (sim / "env_h1.log").read_text().splitlines()
    assert all(ln.startswith("2 ") for ln in lines1), lines1
    for i in range(3):
        lines = (sim / f"env_h{i}.log").read_text().splitlines()
        e2 = [ln for ln in lines if ln.startswith("2 ")][-1]
        assert "members=0,1,2" in e2 and "nproc=-" in e2, e2

    # batch-exactness across BOTH churn boundaries: each epoch's tail
    # consumed exactly [agreed resume, ...) — contiguous from the
    # restored cursor, with the final epoch reaching the end
    for ep, hosts in ((1, (0, 2)), (2, (0, 1, 2))):
        agreed = json.loads(
            (launch / "agree" / f"resume-podrejoin-e{ep}.json").read_text()
        )["value"]
        if agreed is not None:
            cursor = ckpt.read_cursor(sim / "ckpt", "podrejoin", agreed)
            assert cursor is not None and cursor["step"] == agreed
        start = 0 if agreed is None else agreed
        for i in hosts:
            tail = [s for e, s in _read_consumed(sim, i) if e == ep]
            assert tail == list(range(start, start + len(tail))), (
                f"h{i} e{ep} replayed or skipped batches: {tail} "
                f"(agreed resume {agreed})"
            )
            if ep == 2:
                assert tail and tail[-1] == steps - 1, (i, tail)

    # observability: the supervisor stream timeline surfaces the whole
    # grow cycle — the joiner's join_request, the leader's peer_join,
    # and per-repoch memberships on the restart markers (the rendered
    # watch frame keeps only the LAST few incidents, so assert over the
    # full folded timeline's labels; the frame itself must carry the
    # grow epoch's membership)
    from ddl_tpu.obs.fold import fold_job
    from ddl_tpu.obs.pod import _timeline_label, pod_summary_from_fold
    from ddl_tpu.obs.watch import build_frame

    fold = fold_job(sim / "suplogs", "podrejoin", cache=False)
    labels = [
        _timeline_label(e)
        for e in pod_summary_from_fold(fold)["timeline"]
    ]
    assert any(lb.startswith("join_request") for lb in labels), labels
    assert any(lb.startswith("peer_join hosts=[1]") for lb in labels), labels
    assert any(
        "peer_join -> epoch 2" in lb and "hosts=[0, 1, 2]" in lb
        for lb in labels
    ), labels
    frame = build_frame(fold, "podrejoin")
    assert "hosts=[0, 1, 2]" in frame, frame

    # goodput (round 20 ledger): the joiner's grow-epoch incarnation
    # books its relaunch into restart_gap/barrier and its re-shard
    # restore into checkpoint — not into untracked
    from ddl_tpu.obs.goodput import ledger_from_fold

    agreed2 = json.loads(
        (launch / "agree" / "resume-podrejoin-e2.json").read_text()
    )["value"]
    ledger = ledger_from_fold(fold_job(sim / "logs_h1", "podrejoin",
                                       cache=False))
    e2_inc = [a for a in ledger["incarnations"] if a["repoch"] == 2]
    assert e2_inc, ledger["incarnations"]
    acc = e2_inc[0]
    assert sum(acc["seconds"].values()) == pytest.approx(
        acc["wall_s"], abs=1e-9
    )
    assert acc["seconds"]["untracked"] >= -0.01 * max(acc["wall_s"], 1e-9)
    assert (acc["seconds"]["restart_gap"] + acc["seconds"]["barrier"]) > 0
    if agreed2 is not None:
        assert acc["seconds"]["checkpoint"] > 0, acc
