"""Ring attention vs full-sequence softmax attention (exact parity)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh

from ddl_tpu.parallel.ring_attention import make_ring_self_attention

B, T, H, D = 2, 32, 3, 8  # global sequence length T over 4 devices


def full_attention(q, k, v, causal):
    scores = np.einsum("bqhd,bkhd->bhqk", q, k) / np.sqrt(D)
    if causal:
        tq = np.arange(T)
        scores = np.where(tq[None, :] <= tq[:, None], scores, -np.inf)
    p = np.exp(scores - scores.max(-1, keepdims=True))
    p /= p.sum(-1, keepdims=True)
    return np.einsum("bhqk,bkhd->bqhd", p, v)


@pytest.fixture(scope="module")
def qkv():
    rng = np.random.default_rng(0)
    return tuple(
        rng.normal(size=(B, T, H, D)).astype(np.float32) for _ in range(3)
    )


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("n_dev", [2, 4, 8])
def test_ring_matches_full(qkv, causal, n_dev):
    q, k, v = qkv
    mesh = Mesh(np.array(jax.devices()[:n_dev]), ("seq",))
    fn = make_ring_self_attention(mesh, causal=causal)
    out = np.asarray(fn(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v)))
    want = full_attention(q, k, v, causal)
    np.testing.assert_allclose(out, want, atol=2e-5, rtol=1e-4)


def test_ring_attention_differentiable():
    """Grad flows through the ring (the training path for long-context)."""
    rng = np.random.default_rng(1)
    q, k, v = (jnp.asarray(rng.normal(size=(1, 16, 2, 4)), jnp.float32) for _ in range(3))
    mesh = Mesh(np.array(jax.devices()[:4]), ("seq",))
    fn = make_ring_self_attention(mesh, causal=True)

    g = jax.grad(lambda a, b, c: fn(a, b, c).sum())(q, k, v)
    assert g.shape == q.shape and bool(jnp.isfinite(g).all())

    # compare against grad of dense reference
    def dense(a, b, c):
        scores = jnp.einsum("bqhd,bkhd->bhqk", a, b) / 2.0
        tq = jnp.arange(16)
        scores = jnp.where(tq[None, :] <= tq[:, None], scores, -jnp.inf)
        p = jax.nn.softmax(scores, axis=-1)
        return jnp.einsum("bhqk,bkhd->bqhd", p, c).sum()

    g_ref = jax.grad(dense)(q, k, v)
    np.testing.assert_allclose(np.asarray(g), np.asarray(g_ref), atol=3e-5, rtol=1e-3)


@pytest.mark.parametrize("use_flash", [False, True])
def test_ring_gqa_matches_repeated_kv(use_flash):
    """Grouped K/V through the ring (dense blocks and flash-in-ring) equals
    repeat-then-attend, while the ppermute hops carry only Hkv heads."""
    rng = np.random.default_rng(7)
    hq, hkv = 4, 2
    q = jnp.asarray(rng.normal(size=(2, 32, hq, 8)), jnp.float32)
    k = jnp.asarray(rng.normal(size=(2, 32, hkv, 8)), jnp.float32)
    v = jnp.asarray(rng.normal(size=(2, 32, hkv, 8)), jnp.float32)
    mesh = Mesh(np.array(jax.devices()[:4]), ("seq",))
    fn = make_ring_self_attention(mesh, causal=True, use_flash=use_flash)
    grouped = np.asarray(fn(q, k, v))
    repeated = np.asarray(
        fn(q, jnp.repeat(k, hq // hkv, 2), jnp.repeat(v, hq // hkv, 2))
    )
    np.testing.assert_allclose(grouped, repeated, atol=2e-5, rtol=1e-4)
    # gradients agree with the repeated-K/V formulation (group-summed)
    gq, gk, gv = jax.grad(lambda a, b, c: fn(a, b, c).sum(), (0, 1, 2))(
        q, k, v
    )
    rq, rk, rv = jax.grad(
        lambda a, b, c: fn(
            a, jnp.repeat(b, hq // hkv, 2), jnp.repeat(c, hq // hkv, 2)
        ).sum(),
        (0, 1, 2),
    )(q, k, v)
    np.testing.assert_allclose(np.asarray(gq), np.asarray(rq), atol=2e-5)
    np.testing.assert_allclose(np.asarray(gk), np.asarray(rk), atol=2e-5)
    np.testing.assert_allclose(np.asarray(gv), np.asarray(rv), atol=2e-5)


def test_ring_gqa_window_matches_dense():
    """Grouped K/V + sliding window through the dense-block ring."""
    from ddl_tpu.ops.attention import dense_attention

    rng = np.random.default_rng(8)
    q = jnp.asarray(rng.normal(size=(1, 32, 4, 8)), jnp.float32)
    k = jnp.asarray(rng.normal(size=(1, 32, 2, 8)), jnp.float32)
    v = jnp.asarray(rng.normal(size=(1, 32, 2, 8)), jnp.float32)
    mesh = Mesh(np.array(jax.devices()[:4]), ("seq",))
    fn = make_ring_self_attention(mesh, causal=True, window=8)
    out = np.asarray(fn(q, k, v))
    want = np.asarray(dense_attention(q, k, v, causal=True, window=8))
    np.testing.assert_allclose(out, want, atol=2e-5, rtol=1e-4)
