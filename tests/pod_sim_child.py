"""Trainer child for the 3-host pod-recovery simulation (test_coord.py).

One "host" of a simulated pod: a real tiny-LM trainer (1 CPU device per
process) under pod supervision.  The hosts share one checkpoint store
(the tmpdir "NAS"), with host 0 as the snapshot writer — the single-
process analog of a pod's collective Orbax save — and every host logging
each consumed batch (the global step, since the LM stream is pure in
step) to ``consumed_h<i>.log`` so the test can audit exact resume:
no batch replayed, none skipped.

Steps are paced (``DDL_SIM_PACE`` seconds each) so the pod's hosts are
genuinely mid-training when one host's injected ``stall@step`` trips
the watchdog — the coordinated-kill path, not a staggered-completion
artifact.  Not collected by pytest (no ``test_`` prefix).
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from ddl_tpu.launch import force_cpu_devices  # noqa: E402

force_cpu_devices(1)

import jax  # noqa: E402

# share the suite's persistent compile cache: generation-0 children must
# not spend longer compiling than the watchdog deadline
_cache = os.environ.get("DDL_TEST_COMPILE_CACHE")
if _cache:
    try:
        jax.config.update("jax_compilation_cache_dir", _cache)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
    except Exception:
        pass

import optax  # noqa: E402

from ddl_tpu.models.transformer import LMConfig  # noqa: E402
from ddl_tpu.parallel.sharding import LMMeshSpec  # noqa: E402
from ddl_tpu.train.lm_trainer import LMRunConfig, LMTrainer  # noqa: E402


def main() -> None:
    sim = os.environ["DDL_SIM_DIR"]
    host = int(os.environ.get("DDL_COORD_HOST", "0"))
    pace = float(os.environ.get("DDL_SIM_PACE", "0"))
    steps = int(os.environ.get("DDL_SIM_STEPS", "10"))
    epoch = os.environ.get("DDL_RESTART_EPOCH", "0")

    # membership/respec audit: record what the supervisor's spawn env
    # said about this incarnation's world (the elastic e2e asserts the
    # epoch-1 relaunch carried the shrunken membership and the
    # renumbered SPMD bootstrap vars)
    with open(os.path.join(sim, f"env_h{host}.log"), "a") as fh:
        fh.write(
            f"{epoch} members={os.environ.get('DDL_COORD_MEMBERS', '-')} "
            f"nproc={os.environ.get('DDL_NUM_PROCESSES', '-')} "
            f"pid={os.environ.get('DDL_PROCESS_ID', '-')}\n"
        )

    # elastic scale-UP drill (DDL_FAULT="rejoin@epoch:K"): once this
    # incarnation's restart epoch reaches K, leave the pod on purpose —
    # the supervisor sees EXIT_REJOIN, proposes its own eviction, and
    # rejoins through the join_request path.  Checked BEFORE training so
    # the leave lands at a restart boundary (a committed snapshot), and
    # consume-on-fire means the post-grow relaunch trains normally.
    from ddl_tpu.utils import faultinject

    if faultinject.check_epoch(int(epoch)):
        from ddl_tpu.supervisor import EXIT_REJOIN

        print(f"[child h{host}] injected rejoin at epoch {epoch}",
              flush=True)
        sys.exit(EXIT_REJOIN)

    cfg = LMConfig(
        vocab_size=256, d_model=16, n_layers=1, n_heads=2, head_dim=8,
        d_ff=32, compute_dtype="float32", remat=False,
    )
    run = LMRunConfig(
        batch=2, seq_len=8, steps=steps, save_every=3, log_every=1,
        job_id=os.environ.get("DDL_JOB_ID", "podsim"),
        checkpoint_dir=os.path.join(sim, "ckpt"),  # the shared "NAS"
        log_dir=os.path.join(sim, f"logs_h{host}"),
    )
    t = LMTrainer(cfg, LMMeshSpec(), optax.adam(1e-2), run)

    # audit trail: every batch this incarnation consumes, keyed by the
    # global step (the LM data cursor), tagged with the restart epoch
    consumed = os.path.join(sim, f"consumed_h{host}.log")
    orig_sample = t._sample_batch

    def sample(step):
        with open(consumed, "a") as fh:
            fh.write(f"{epoch} {step}\n")
            fh.flush()
        return orig_sample(step)

    t._sample_batch = sample

    if pace > 0:
        fns = t.fns
        orig_train = fns.train

        def paced(state, inp, tgt):
            time.sleep(pace)
            return orig_train(state, inp, tgt)

        t.fns = fns._replace(train=paced)

    if host != 0:
        # hosts 1+ read the shared store but never write it: the single-
        # process stand-in for a pod's rank-coordinated collective save
        t.save_snapshot = lambda period: None

    print(f"[child h{host}] start at step {t._start_step} "
          f"(restart epoch {epoch})", flush=True)
    t.train()
    final = int(jax.device_get(t.state.step))
    # the decisive cross-host check: a sha256 over the full param state —
    # identical final step AND identical weights on every host
    import hashlib

    import numpy as np

    h = hashlib.sha256()
    for leaf in jax.tree.leaves(jax.device_get(t.state.params)):
        h.update(np.ascontiguousarray(leaf).tobytes())
    with open(os.path.join(sim, f"final_h{host}.log"), "a") as fh:
        fh.write(f"{epoch} {final} {h.hexdigest()}\n")
    print(f"[child h{host}] CHILD_OK step={final}", flush=True)
    if t.preempted and os.environ.get("DDL_SUPERVISED") == "1":
        from ddl_tpu.supervisor import EXIT_PREEMPTED

        sys.exit(EXIT_PREEMPTED)


if __name__ == "__main__":
    main()
