"""Sliding-window attention (LMConfig.attn_window, the Mistral recipe):
band-masked causal attention across every core — dense, flash kernel
(block-skip), ring (global-position band across hops), Ulysses, and the
decode cache — all equal to the dense reference."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ddl_tpu.ops.attention import dense_attention
from ddl_tpu.ops.flash_attention import flash_attention

W = 8


@pytest.fixture(scope="module")
def qkv():
    rng = np.random.default_rng(0)
    shape = (2, 64, 2, 8)
    return tuple(
        jnp.asarray(rng.normal(size=shape), jnp.float32) for _ in range(3)
    )


def _dense_banded(q, k, v, window):
    """Independent reference: explicit band mask fed to dense_attention."""
    t = q.shape[1]
    pos = np.arange(t)
    mask = (pos[None, :] <= pos[:, None]) & (pos[None, :] > pos[:, None] - window)
    return dense_attention(q, k, v, mask=jnp.asarray(mask))


def test_dense_window_matches_explicit_band(qkv):
    q, k, v = qkv
    out = dense_attention(q, k, v, causal=True, window=W)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(_dense_banded(q, k, v, W)), atol=1e-6
    )
    # window >= T degenerates to plain causal
    full = dense_attention(q, k, v, causal=True, window=4096)
    plain = dense_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(full), np.asarray(plain), atol=1e-6)
    with pytest.raises(ValueError, match="causal"):
        dense_attention(q, k, v, causal=False, window=W)
    # an explicit mask would silently override the band: reject the combo
    with pytest.raises(ValueError, match="explicit mask"):
        dense_attention(
            q, k, v, causal=True, window=W,
            mask=jnp.tril(jnp.ones((q.shape[1], q.shape[1]), bool)),
        )


@pytest.mark.parametrize("window", [4, 8, 24])
def test_flash_window_matches_dense(qkv, window):
    """Band-masked kernel (incl. block skipping: window 4 < block 16 skips
    whole past blocks) == dense band, forward and gradients."""
    q, k, v = qkv
    out = flash_attention(
        q, k, v, causal=True, window=window, block_q=16, block_k=16
    )
    want = _dense_banded(q, k, v, window)
    np.testing.assert_allclose(np.asarray(out), np.asarray(want), atol=2e-5)

    cot = jnp.asarray(np.random.default_rng(1).normal(size=q.shape), jnp.float32)
    gf = jax.grad(
        lambda *a: (flash_attention(
            *a, causal=True, window=window, block_q=16, block_k=16
        ) * cot).sum(),
        (0, 1, 2),
    )(q, k, v)
    gd = jax.grad(
        lambda *a: (_dense_banded(*a, window) * cot).sum(), (0, 1, 2)
    )(q, k, v)
    for a, b in zip(gf, gd):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=3e-5)


def test_ring_window_matches_dense(qkv):
    """The ring's global-position band: window spans ring-block boundaries."""
    from jax.sharding import Mesh

    from ddl_tpu.parallel.ring_attention import make_ring_self_attention

    q, k, v = qkv
    mesh = Mesh(np.array(jax.devices()[:4]), ("seq",))
    ring = make_ring_self_attention(mesh, causal=True, window=W)
    np.testing.assert_allclose(
        np.asarray(ring(q, k, v)), np.asarray(_dense_banded(q, k, v, W)),
        atol=1e-5,
    )


def test_ulysses_window_matches_dense(qkv):
    from jax.sharding import Mesh

    from ddl_tpu.parallel.ulysses import make_ulysses_self_attention

    q, k, v = qkv
    mesh = Mesh(np.array(jax.devices()[:2]), ("seq",))
    uly = make_ulysses_self_attention(mesh, causal=True, window=W)
    np.testing.assert_allclose(
        np.asarray(uly(q, k, v)), np.asarray(_dense_banded(q, k, v, W)),
        atol=1e-5,
    )


@pytest.mark.parametrize("n_dev,window", [(2, 8), (4, 8), (4, 24), (4, 100)])
def test_ring_flash_window_matches_dense(n_dev, window):
    """Flash-in-ring with a sliding window (the round-2 ValueError, now a
    feature): each hop runs the kernel banded in its own coordinates via
    kv_offset, the ring truncates to O(window) hops, and the result equals
    single-device banded attention — including windows smaller than,
    spanning, and exceeding the T_local block (and the full sequence)."""
    from jax.sharding import Mesh

    from ddl_tpu.parallel.ring_attention import make_ring_self_attention

    rng = np.random.default_rng(5)
    q, k, v = (
        jnp.asarray(rng.normal(size=(2, 32, 2, 8)), jnp.float32)
        for _ in range(3)
    )
    mesh = Mesh(np.array(jax.devices()[:n_dev]), ("seq",))
    fn = make_ring_self_attention(
        mesh, causal=True, use_flash=True, window=window, flash_block=8
    )
    want = _dense_banded(q, k, v, window)
    np.testing.assert_allclose(
        np.asarray(fn(q, k, v)), np.asarray(want), atol=2e-5, rtol=1e-4
    )
    # differentiable (the training path)
    g = jax.grad(lambda a, b, c: fn(a, b, c).sum(), (0, 1, 2))(q, k, v)
    gd = jax.grad(
        lambda a, b, c: _dense_banded(a, b, c, window).sum(), (0, 1, 2)
    )(q, k, v)
    for a, b in zip(g, gd):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=3e-5, rtol=1e-4
        )


def test_config_window_requires_causal():
    from ddl_tpu.models.transformer import LMConfig

    with pytest.raises(ValueError, match="attn_window"):
        LMConfig(causal=False, attn_window=W)
    with pytest.raises(ValueError, match=">= 0"):
        LMConfig(attn_window=-1)


def test_lm_ring_flash_window_matches_dense_model():
    """Full model: flash-in-ring + attn_window on a seq=2 mesh reproduces
    the single-device dense-windowed run (the round-2 factory ValueError
    is now a supported composition)."""
    import optax

    from ddl_tpu.models.transformer import LMConfig
    from ddl_tpu.parallel.sharding import LMMeshSpec
    from ddl_tpu.train.lm_steps import make_lm_step_fns

    def run(spec, **kw):
        cfg = LMConfig(
            vocab_size=32, d_model=32, n_layers=2, n_heads=4, head_dim=8,
            d_ff=64, compute_dtype="float32", remat=False, attn_window=W,
            **kw,
        )
        fns = make_lm_step_fns(
            cfg, spec, optax.adam(1e-3), jax.random.key(0), 4, 32,
            devices=jax.devices()[: spec.num_devices],
        )
        rng = np.random.default_rng(0)
        x = rng.integers(0, 32, (4, 33))
        _, m = fns.train(
            fns.init_state(), jnp.asarray(x[:, :-1]), jnp.asarray(x[:, 1:])
        )
        return float(m["loss"])

    ref = run(LMMeshSpec())
    got = run(LMMeshSpec(seq=2), attn_impl="ring", flash=True)
    np.testing.assert_allclose(got, ref, atol=1e-4)


def test_lm_windowed_decode_matches_training_forward():
    """End to end: a windowed LM's cached incremental decode reproduces its
    training forward token by token (both paths apply the same band)."""
    from ddl_tpu.infer import LMDecode, init_kv_cache
    from ddl_tpu.models.transformer import LMConfig, TransformerLM

    cfg = LMConfig(
        vocab_size=32, d_model=16, n_layers=2, n_heads=2, head_dim=8,
        d_ff=32, compute_dtype="float32", remat=False, attn_window=4,
    )
    b, t = 2, 12  # window 4 << t: the band actually bites
    model = TransformerLM(cfg, None)
    import flax.linen as nn

    dummy = jnp.zeros((b, t), jnp.int32)
    params = nn.meta.unbox(model.init(jax.random.key(0), dummy)["params"])
    toks = jnp.asarray(np.random.default_rng(2).integers(0, 32, (b, t)))
    ref_logits, _ = model.apply({"params": params}, toks)

    # windowed must differ from unwindowed (sanity that the band applies)
    import dataclasses

    full_model = TransformerLM(dataclasses.replace(cfg, attn_window=0), None)
    full_logits, _ = full_model.apply({"params": params}, toks)
    assert float(np.abs(np.asarray(ref_logits - full_logits)).max()) > 1e-3

    caches = init_kv_cache(cfg, b, t)
    dec = LMDecode(cfg)
    for i in range(t):
        logits, caches = dec.apply(
            {"params": params}, toks[:, i : i + 1], caches, i
        )
        np.testing.assert_allclose(
            np.asarray(logits[:, 0]), np.asarray(ref_logits[:, i]), atol=1e-5
        )


def test_lm_windowed_training_sharded_matches_single():
    """Windowed LM under (data=2, seq=2) ring SP == single device."""
    import optax

    from ddl_tpu.models.transformer import LMConfig
    from ddl_tpu.parallel.sharding import LMMeshSpec
    from ddl_tpu.train.lm_steps import make_lm_step_fns

    losses = {}
    for name, spec, attn in (
        ("single", LMMeshSpec(), "dense"),
        ("ring", LMMeshSpec(data=2, seq=2), "ring"),
        ("ulysses", LMMeshSpec(data=2, seq=2), "ulysses"),
    ):
        cfg = LMConfig(
            vocab_size=32, d_model=32, n_layers=2, n_heads=4, head_dim=8,
            d_ff=64, compute_dtype="float32", remat=False,
            attn_impl=attn, attn_window=8,
        )
        fns = make_lm_step_fns(
            cfg, spec, optax.adam(1e-3), jax.random.key(0), 4, 32,
            devices=jax.devices()[: spec.num_devices],
        )
        toks = jnp.asarray(np.random.default_rng(0).integers(0, 32, (4, 33)))
        _, m = fns.train(fns.init_state(), toks[:, :-1], toks[:, 1:])
        losses[name] = float(m["loss"])
    assert abs(losses["single"] - losses["ring"]) < 1e-4
    assert abs(losses["single"] - losses["ulysses"]) < 1e-4


def test_windowed_generation_matches_full_cache_model():
    """make_lm_generator with a windowed config: greedy generation through
    the O(window) cache slice equals greedy next-token argmax of the same
    windowed model's training forward at every step."""
    import flax.linen as nn

    from ddl_tpu.infer import make_lm_generator
    from ddl_tpu.models.transformer import LMConfig, TransformerLM

    cfg = LMConfig(
        vocab_size=32, d_model=16, n_layers=2, n_heads=2, head_dim=8,
        d_ff=32, compute_dtype="float32", remat=False, attn_window=4,
    )
    b, prompt_len, max_new = 2, 6, 8
    model = TransformerLM(cfg, None)
    params = nn.meta.unbox(
        model.init(jax.random.key(0), jnp.zeros((b, prompt_len), jnp.int32))
        ["params"]
    )
    prompt = jnp.asarray(
        np.random.default_rng(3).integers(0, 32, (b, prompt_len))
    )
    gen = make_lm_generator(
        cfg, prompt_len=prompt_len, max_new=max_new, batch=b
    )
    out = np.asarray(gen(params, prompt, jax.random.key(1)))

    # teacher-forcing reference: feed the growing sequence through the
    # training forward and take argmax of the last position each step
    seq = np.asarray(prompt)
    for i in range(max_new):
        logits, _ = model.apply({"params": params}, jnp.asarray(seq))
        nxt = np.argmax(np.asarray(logits[:, -1]), -1)
        np.testing.assert_array_equal(out[:, i], nxt, err_msg=f"step {i}")
        seq = np.concatenate([seq, nxt[:, None]], axis=1)
