"""Goodput ledger (obs/goodput.py + the fold's v8 reducer): exhaustive
per-(host, repoch) chip-time accounting with badput attribution.

Load-bearing properties:

* every incarnation's buckets sum EXACTLY to its wall clock (the
  residual is the ``untracked`` bucket, reported, never dropped);
* warm (sidecar-resumed) folds render byte-identically to a cold parse
  under arbitrary append/truncate/recreate histories;
* replay charging is cursor-exact: an exact preemption resume charges
  nothing, a crash resumed from an older snapshot reclassifies the lost
  periods as ``rolled_back``;
* every surface (goodput/summarize/watch/export/fleet/diff gate)
  renders the same account from one fold.
"""

import json
import math
import os
import sys
import threading
import time
import urllib.request

import pytest

# ---------------------------------------------------------------------------
# synthetic streams
# ---------------------------------------------------------------------------


def _ev(host, kind, ts, **kw):
    e = {
        "ts": ts, "mono": ts, "run": kw.pop("run", f"r{host}"),
        "host": host, "step": kw.pop("step", None), "kind": kind,
    }
    e.update(kw)
    return e


def _period(host, ts, p, *, repoch=None, steps=10, offset=0, step_s=6.0,
            fence_s=1.0, data_s=1.5, compile_s=0.0, **kw):
    phases = {"step": step_s, "fence": fence_s, "data_wait": data_s}
    phases.update(kw.pop("phases", {}))
    return _ev(
        host, "period", ts, step=p, period=p, steps=steps, offset=offset,
        elapsed=step_s + fence_s + data_s, steps_per_sec=1.0,
        phases=phases, compiles=1 if compile_s else 0,
        compile_s=compile_s, loss=2.0,
        **({"repoch": repoch} if repoch else {}), **kw,
    )


def _goodput_events(host, *, offset=0.0):
    """A two-incarnation stream exercising every ledger input: periods
    with compile seconds, an in-loop rollback, a stall, a restart
    decision + join barrier + snapshot restore into repoch 1, and a
    decode tail."""
    o = offset
    evs = [_ev(host, "run_start", 10.0 + o, family="lm")]
    evs.append(_period(host, 20.0 + o, 0, compile_s=2.0))
    evs.append(_period(host, 30.0 + o, 1))
    # non-finite period 2: rollback to 1, the bad period event follows
    evs.append(_ev(
        host, "rollback", 39.0 + o, step=2, period=2, resumed_at=1,
        restore_dur=0.4, grace_scale=0.1, grace_periods=2,
    ))
    evs.append(_period(host, 40.0 + o, 2))
    evs.append(_period(host, 50.0 + o, 1))  # re-run after rollback
    evs.append(_ev(
        host, "stall", 58.0 + o, step=22, age=5.0, deadline=4.0,
        action="exit", stacks={"t": "tb"},
    ))
    evs.append(_ev(host, "run_end", 60.0 + o, phases={}, anomalies=0))
    # pod restart into repoch 1: decision 62, join barrier, child at 66
    evs.append(_ev(
        host, "supervisor_relaunch", 62.0 + o, reason="preempt", rc=75,
        delay=0.0, decision_ts=62.0 + o,
    ))
    evs.append(_ev(
        host, "coord_barrier", 65.0 + o, name="e1-join", wait=1.5,
        completed_ts=65.0 + o,
    ))
    evs.append(_ev(host, "run_start", 66.0 + o, family="lm", repoch=1))
    evs.append(_ev(
        host, "snapshot_restore", 66.6 + o, dur=0.6, epoch=2, period=3,
        offset=0, repoch=1,
    ))
    evs.append(_ev(
        host, "restart_latency", 75.0 + o, step=30, latency=13.0,
        decision_ts=62.0 + o, repoch=1,
    ))
    evs.append(_period(host, 76.0 + o, 3, repoch=1, compile_s=3.0))
    evs.append(_ev(
        host, "decode", 80.0 + o, prompt_len=8, new_tokens=16, batch=1,
        dur=2.0, queue_delay=0.0, ttft=0.3, tok_per_s=8.0, warm=True,
        chips=1, repoch=1,
    ))
    evs.append(_ev(host, "run_end", 81.0 + o, phases={}, anomalies=0,
                   repoch=1))
    return evs


def _append(log_dir, job, host, lines, torn=None):
    d = log_dir / "by_job_id" / job
    d.mkdir(parents=True, exist_ok=True)
    with open(d / f"events-h{host:03d}.jsonl", "a") as f:
        for ln in lines:
            f.write(ln + "\n")
        if torn is not None:
            f.write(torn)
    return d / f"events-h{host:03d}.jsonl"


def _render_all(log_dir, job, cache):
    from ddl_tpu.obs.fold import fold_job
    from ddl_tpu.obs.goodput import ledger_from_fold, render_goodput
    from ddl_tpu.obs.report import render_summary, summarize_from_fold

    fold = fold_job(log_dir, job, cache=cache)
    return (
        render_goodput(ledger_from_fold(fold), job),
        render_summary(summarize_from_fold(fold), job),
        fold,
    )


# ---------------------------------------------------------------------------
# the account itself
# ---------------------------------------------------------------------------


def _assert_exhaustive(ledger):
    """Buckets sum to the wall clock — the acceptance invariant.  The
    residual construction makes the sum exact; the 1%-of-wall bound
    additionally asserts no attribution EXCEEDS the wall (untracked
    must never be meaningfully negative)."""
    for inc in ledger["incarnations"]:
        total = sum(inc["seconds"].values())
        assert total == pytest.approx(inc["wall_s"], abs=1e-9)
        assert inc["seconds"]["untracked"] >= -0.01 * max(
            inc["wall_s"], 1e-9
        )
    job = ledger["job"]
    assert sum(job["seconds"].values()) == pytest.approx(
        job["wall_s"], abs=1e-9
    )


def test_ledger_buckets_and_exhaustiveness(tmp_path):
    from ddl_tpu.obs.fold import fold_job
    from ddl_tpu.obs.goodput import ledger_from_fold

    job = "acct"
    for h in range(2):
        _append(tmp_path, job, h,
                [json.dumps(e) for e in _goodput_events(h, offset=0.01 * h)])
    ledger = ledger_from_fold(fold_job(tmp_path, job, cache=False))
    _assert_exhaustive(ledger)
    assert len(ledger["incarnations"]) == 4  # 2 hosts x 2 repochs

    inc0 = next(
        i for i in ledger["incarnations"]
        if i["host"] == 0 and i["repoch"] == 0
    )
    s = inc0["seconds"]
    # 4 period events x 7.0s step+fence; the rollback reclassifies the
    # pre-rollback period 1 (7.0) plus the pending bad period 2 (7.0)
    assert s["rolled_back"] == pytest.approx(14.0)
    assert s["recompile"] == pytest.approx(2.0)
    assert s["productive"] == pytest.approx(4 * 7.0 - 14.0 - 2.0)
    assert s["data_wait"] == pytest.approx(4 * 1.5)
    assert s["checkpoint"] == pytest.approx(0.4)  # rollback restore
    assert s["stall"] == pytest.approx(5.0)
    assert inc0["wall_s"] == pytest.approx(50.0)  # ts 10 -> 60

    inc1 = next(
        i for i in ledger["incarnations"]
        if i["host"] == 0 and i["repoch"] == 1
    )
    s1 = inc1["seconds"]
    # wall starts at the restart DECISION (62), not the first event (66)
    assert inc1["wall_s"] == pytest.approx(81.0 - 62.0)
    assert s1["barrier"] == pytest.approx(1.5)
    assert s1["restart_gap"] == pytest.approx((66.0 - 62.0) - 1.5)
    assert s1["checkpoint"] == pytest.approx(0.6)  # startup restore
    assert s1["recompile"] == pytest.approx(3.0)
    assert s1["serve"] == pytest.approx(2.0)
    # no replay: the restore cursor (period 3) is past everything saved
    assert s1["rolled_back"] == 0.0

    # job rolls up both hosts' full spans; the sparse synthetic
    # timestamps leave untracked dominant — which is the honest answer
    assert ledger["job"]["wall_s"] == pytest.approx(2 * 71.0, abs=0.1)
    assert ledger["job"]["dominant_badput"][0] == "untracked"
    from ddl_tpu.obs.goodput import dominant_badput

    tracked = dict(ledger["job"]["seconds"], untracked=0.0)
    assert dominant_badput(tracked)[0] == "rolled_back"


def test_replay_charging_is_cursor_exact(tmp_path):
    """Crash resumed from an older snapshot charges the lost periods;
    an exact preemption resume (coverage ends at the cursor) charges
    nothing; partial coverage charges the lost fraction."""
    from ddl_tpu.obs.fold import fold_job
    from ddl_tpu.obs.goodput import ledger_from_fold

    def led(job, evs):
        _append(tmp_path, job, 0, [json.dumps(e) for e in evs])
        return ledger_from_fold(fold_job(tmp_path, job, cache=False))

    # crash: snapshot at period-1 boundary, periods 1..2 lost
    evs = [_ev(0, "run_start", 10.0)]
    for p in range(3):
        evs.append(_period(0, 20.0 + 10 * p, p, step_s=3.0, fence_s=0.5,
                           data_s=1.0))
    evs.append(_ev(0, "run_start", 50.0, repoch=1))
    evs.append(_ev(0, "snapshot_restore", 50.5, dur=0.5, epoch=1,
                   period=1, offset=0, repoch=1))
    L = led("crash", evs)
    e0 = next(i for i in L["incarnations"] if i["repoch"] == 0)
    assert e0["seconds"]["rolled_back"] == pytest.approx(2 * 3.5)
    _assert_exhaustive(L)

    # exact preempt: period 0 ran 6 steps, cursor says (0, 6) -> nothing
    evs = [
        _ev(0, "run_start", 10.0),
        _period(0, 20.0, 0, steps=6),
        _ev(0, "run_start", 30.0, repoch=1),
        _ev(0, "snapshot_restore", 30.5, dur=0.3, epoch=0, period=0,
            offset=6, repoch=1),
    ]
    L = led("preempt", evs)
    e0 = next(i for i in L["incarnations"] if i["repoch"] == 0)
    assert e0["seconds"]["rolled_back"] == 0.0

    # partial: the old event covered [2, 10) of period 0, the cursor
    # saved up to 6 -> half its step time is lost
    evs = [
        _ev(0, "run_start", 10.0),
        _period(0, 20.0, 0, steps=8, offset=2),
        _ev(0, "run_start", 30.0, repoch=1),
        _ev(0, "snapshot_restore", 30.5, dur=0.3, epoch=0, period=0,
            offset=6, repoch=1),
    ]
    L = led("partial", evs)
    e0 = next(i for i in L["incarnations"] if i["repoch"] == 0)
    assert e0["seconds"]["rolled_back"] == pytest.approx(7.0 * 0.5)

    # a SECOND restore to the same cursor must not double-charge ground
    # already charged (the popped entries are gone)
    evs = [_ev(0, "run_start", 10.0)]
    for p in range(3):
        evs.append(_period(0, 20.0 + 10 * p, p, step_s=3.0, fence_s=0.5,
                           data_s=1.0))
    evs.append(_ev(0, "run_start", 50.0, repoch=1))
    evs.append(_ev(0, "snapshot_restore", 50.5, dur=0.5, epoch=1,
                   period=1, offset=0, repoch=1))
    evs.append(_period(0, 60.0, 1, repoch=1, step_s=3.0, fence_s=0.5,
                       data_s=1.0))
    evs.append(_ev(0, "run_start", 70.0, repoch=2))
    evs.append(_ev(0, "snapshot_restore", 70.5, dur=0.5, epoch=1,
                   period=1, offset=0, repoch=2))
    L = led("twice", evs)
    e0 = next(i for i in L["incarnations"] if i["repoch"] == 0)
    e1 = next(i for i in L["incarnations"] if i["repoch"] == 1)
    assert e0["seconds"]["rolled_back"] == pytest.approx(2 * 3.5)
    # repoch 1's own re-run of period 1 is lost to the second crash
    assert e1["seconds"]["rolled_back"] == pytest.approx(3.5)


def test_dump_mode_stall_not_double_counted(tmp_path):
    """A dump-mode stall the process RECOVERS from must not be charged:
    the recovered phase later reports the hang inside its own duration,
    and charging both would attribute the same wall clock twice (the
    stall bucket is exit-escalations only)."""
    from ddl_tpu.obs.fold import fold_job
    from ddl_tpu.obs.goodput import ledger_from_fold

    evs = [
        _ev(0, "run_start", 10.0),
        _ev(0, "stall", 140.0, step=5, age=121.0, deadline=120.0,
            action="dump", stacks={}),
        # the hung step recovered: its period covers the hang
        _period(0, 160.0, 0, step_s=140.0, fence_s=1.0, data_s=1.0),
    ]
    _append(tmp_path, "dump", 0, [json.dumps(e) for e in evs])
    L = ledger_from_fold(fold_job(tmp_path, "dump", cache=False))
    _assert_exhaustive(L)
    inc = L["incarnations"][0]
    assert inc["seconds"]["stall"] == 0.0
    assert inc["seconds"]["productive"] == pytest.approx(141.0)


def test_partial_charge_keeps_saved_slice_for_deeper_restore(tmp_path):
    """An exact-resume restore must not ERASE the saved coverage it did
    not charge: a later, deeper restore still charges it.  (Regression:
    _charge_replay used to pop boundary-straddling records whole.)"""
    from ddl_tpu.obs.fold import fold_job
    from ddl_tpu.obs.goodput import ledger_from_fold

    evs = [
        _ev(0, "run_start", 10.0),
        # period 0 ran [0, 6) — 6.0s of step+fence
        _period(0, 20.0, 0, steps=6, offset=0, step_s=5.0, fence_s=1.0,
                data_s=1.0),
        # exact preemption resume at (0, 6): charges nothing
        _ev(0, "run_start", 30.0, repoch=1),
        _ev(0, "snapshot_restore", 30.5, dur=0.2, epoch=0, period=0,
            offset=6, repoch=1),
        # ... then a crash resumed from SCRATCH: cursor (0, 0) must
        # still charge repoch 0's saved [0, 6) coverage
        _ev(0, "run_start", 40.0, repoch=2),
        _ev(0, "snapshot_restore", 40.5, dur=0.2, epoch=None, period=0,
            offset=0, repoch=2),
    ]
    _append(tmp_path, "deep", 0, [json.dumps(e) for e in evs])
    L = ledger_from_fold(fold_job(tmp_path, "deep", cache=False))
    e0 = next(i for i in L["incarnations"] if i["repoch"] == 0)
    assert e0["seconds"]["rolled_back"] == pytest.approx(6.0)
    _assert_exhaustive(L)


def test_fractions_sum_property_on_synthetic_multi_incarnation(tmp_path):
    """Property test: across a family of synthetic multi-host,
    multi-incarnation streams (varying period counts, rollbacks,
    restarts, stalls, decode tails), every incarnation's bucket
    fractions sum to 1 and the job account stays exhaustive."""
    from ddl_tpu.obs.fold import fold_job
    from ddl_tpu.obs.goodput import ledger_from_fold

    for case in range(6):
        job = f"prop{case}"
        hosts = 1 + case % 3
        for h in range(hosts):
            evs = [_ev(h, "run_start", 10.0)]
            t = 20.0
            for p in range(2 + case):
                evs.append(_period(
                    h, t, p, compile_s=0.5 if p == 0 else 0.0,
                    step_s=3.0 + p, fence_s=0.5,
                ))
                t += 6.0 + p
            if case % 2:
                evs.append(_ev(
                    h, "rollback", t, step=1, period=1, resumed_at=0,
                    restore_dur=0.2, grace_scale=0.1, grace_periods=1,
                ))
                t += 1.0
                evs.append(_period(h, t + 9.0, 1))
                t += 10.0
            if case % 3 == 0:
                evs.append(_ev(h, "stall", t, step=9, age=2.0,
                               deadline=1.0, action="exit", stacks={}))
                t += 2.0
            evs.append(_ev(h, "run_end", t, phases={}, anomalies=0))
            t += 2.0
            for repoch in range(1, 1 + case % 2 + 1):
                evs.append(_ev(
                    h, "run_start", t + 3.0, repoch=repoch, run=f"x{repoch}",
                ))
                evs.append(_ev(
                    h, "snapshot_restore", t + 3.5, dur=0.3, epoch=0,
                    period=1, offset=0, repoch=repoch,
                ))
                evs.append(_ev(
                    h, "restart_latency", t + 6.0, step=5, latency=5.0,
                    decision_ts=t + 1.0, repoch=repoch,
                ))
                evs.append(_period(h, t + 16.0, 1 + repoch, repoch=repoch))
                t += 20.0
            _append(tmp_path, job, h, [json.dumps(e) for e in evs])
        ledger = ledger_from_fold(fold_job(tmp_path, job, cache=False))
        _assert_exhaustive(ledger)
        for inc in ledger["incarnations"]:
            if inc["wall_s"] > 0:
                fracs = {
                    c: v / inc["wall_s"]
                    for c, v in inc["seconds"].items()
                }
                assert sum(fracs.values()) == pytest.approx(1.0)


def test_goodput_warm_cold_byte_identity_under_splits(tmp_path):
    """The v8 sidecar: resumed folds across arbitrary append splits —
    torn line, truncation, recreation — render `obs goodput` AND
    summarize byte-identically to a cold parse at every state."""
    from ddl_tpu.obs.fold import SIDECAR_NAME

    job = "gsplit"
    lines = {
        h: [json.dumps(e) for e in _goodput_events(h, offset=0.001 * h)]
        for h in range(2)
    }
    torn_full = lines[1][5]
    cut = len(torn_full) // 2
    slices = [
        {0: (0, 4, None), 1: (0, 5, torn_full[:cut])},
        {0: (4, 9, None)},
        {h: (None, None, None) for h in range(2)},
    ]
    done = {0: 0, 1: 5}
    for i, sl in enumerate(slices):
        for h, (a, b, torn) in sl.items():
            if a is None:
                a, b = done[h], len(lines[h])
            _append(tmp_path, job, h, lines[h][a:b], torn=torn)
            done[h] = b
        if i == 1:
            _append(tmp_path, job, 1, [], torn=torn_full[cut:] + "\n")
            _append(tmp_path, job, 1, lines[1][6:])
            done[1] = len(lines[1])
        warm_g, warm_s, _ = _render_all(tmp_path, job, cache=True)
        cold_g, cold_s, _ = _render_all(tmp_path, job, cache=False)
        assert warm_g == cold_g, f"goodput diverged at slice {i}"
        assert warm_s == cold_s, f"summarize diverged at slice {i}"
    assert (tmp_path / "by_job_id" / job / SIDECAR_NAME).exists()

    # truncate below the cursor -> clean rebuild
    path = tmp_path / "by_job_id" / job / "events-h000.jsonl"
    path.write_text("\n".join(lines[0][:3]) + "\n")
    warm_g, _, _ = _render_all(tmp_path, job, cache=True)
    cold_g, _, _ = _render_all(tmp_path, job, cache=False)
    assert warm_g == cold_g

    # recreate under the same name with different content
    path.unlink()
    _append(tmp_path, job, 0,
            [json.dumps(e) for e in _goodput_events(0, offset=500.0)])
    warm_g, _, _ = _render_all(tmp_path, job, cache=True)
    cold_g, _, _ = _render_all(tmp_path, job, cache=False)
    assert warm_g == cold_g


def test_period_record_cap_stays_bounded(tmp_path):
    """A week-long run's sidecar must not grow one entry per period:
    the replay record keeps a bounded trailing window, warm==cold
    through the pruning."""
    from ddl_tpu.obs.fold import (
        _GOODPUT_PERIOD_KEEP, SIDECAR_NAME, fold_job,
    )

    job = "cap"
    evs = [_ev(0, "run_start", 10.0)]
    for p in range(400):
        evs.append(_period(0, 20.0 + p, p))
    lines = [json.dumps(e) for e in evs]
    _append(tmp_path, job, 0, lines[:200])
    _render_all(tmp_path, job, cache=True)
    _append(tmp_path, job, 0, lines[200:])
    warm_g, _, _ = _render_all(tmp_path, job, cache=True)
    cold_g, _, _ = _render_all(tmp_path, job, cache=False)
    assert warm_g == cold_g
    sidecar = json.loads(
        (tmp_path / "by_job_id" / job / SIDECAR_NAME).read_text()
    )
    rec = sidecar["streams"]["events-h000.jsonl"]["goodput"]["0"]
    assert len(rec["periods"]) <= 160
    assert len(rec["periods"]) >= _GOODPUT_PERIOD_KEEP
    fold = fold_job(tmp_path, job, cache=True)
    assert fold.streams["events-h000.jsonl"].goodput[0]["phases"][
        "step"
    ] == pytest.approx(400 * 6.0)


# ---------------------------------------------------------------------------
# surfaces: CLI, summarize, watch, export, fleet, gate
# ---------------------------------------------------------------------------


def test_goodput_cli_and_summarize_render_same_account(tmp_path, capsys):
    from ddl_tpu import cli

    job = "surf"
    for h in range(2):
        _append(tmp_path, job, h,
                [json.dumps(e) for e in _goodput_events(h)])
    cli.main(["obs", "goodput", job, "--log-dir", str(tmp_path)])
    out = capsys.readouterr().out
    assert f"goodput — {job}" in out
    for cat in ("productive", "rolled_back", "restart_gap", "untracked"):
        assert cat in out
    # columns per incarnation + job
    assert "h0/e0" in out and "h1/e1" in out and "job" in out

    cli.main(["obs", "goodput", job, "--log-dir", str(tmp_path), "--json"])
    parsed = json.loads(capsys.readouterr().out)
    job_ratio = parsed["job"]["ratio"]
    assert 0.0 < job_ratio < 1.0

    # summarize renders the same job ratio from the same fold
    cli.main(["obs", "summarize", job, "--log-dir", str(tmp_path)])
    s_out = capsys.readouterr().out
    assert f"goodput: {job_ratio:.1%}" in s_out
    assert "top badput:" in s_out

    # watch panel
    cli.main(["obs", "watch", job, "--log-dir", str(tmp_path), "--once"])
    w_out = capsys.readouterr().out
    assert "-- goodput --" in w_out
    assert f"productive: {job_ratio:.1%}" in w_out
    assert "top badput:" in w_out


def test_goodput_export_series_and_fleet_columns(tmp_path, capsys):
    from ddl_tpu import cli

    job = "exp"
    _append(tmp_path, job, 0, [json.dumps(e) for e in _goodput_events(0)])
    cli.main(["obs", "export", job, "--log-dir", str(tmp_path), "--once"])
    out = capsys.readouterr().out
    assert "# TYPE ddl_obs_goodput_seconds gauge" in out
    assert (
        f'ddl_obs_goodput_seconds{{category="rolled_back",host="0",'
        f'job_id="{job}",repoch="0"}} 14' in out
    )
    assert (
        f'ddl_obs_goodput_seconds{{category="barrier",host="0",'
        f'job_id="{job}",repoch="1"}} 1.5' in out
    )
    assert f'ddl_obs_goodput_ratio{{host="0"' in out
    assert f'ddl_obs_goodput_job_ratio{{job_id="{job}"}}' in out
    # categories sum to wall in the scrape too
    import re

    secs = {
        m.group(1): float(m.group(2))
        for m in re.finditer(
            r'ddl_obs_goodput_seconds\{category="(\w+)",host="0",'
            rf'job_id="{job}",repoch="0"\}} ([\d.e+-]+)', out,
        )
    }
    assert sum(secs.values()) == pytest.approx(50.0, abs=1e-6)

    # fleet: goodput + dominant-badput columns from the same summary
    cli.main(["obs", "fleet", str(tmp_path), "--json"])
    fleet = json.loads(capsys.readouterr().out)
    assert 0.0 < fleet[job]["goodput"] < 1.0
    assert fleet[job]["badput"] == "untracked"
    cli.main(["obs", "fleet", str(tmp_path)])
    table = capsys.readouterr().out
    assert "goodput" in table and "badput" in table
    assert "untracked" in table


def test_diff_fail_goodput_drop_gate(tmp_path, capsys):
    """The CI gate: a stall-injected run against a clean baseline fails
    --fail-goodput-drop; a matching run passes; a pre-ledger baseline
    is rejected loudly."""
    from ddl_tpu import cli

    def mk(job, stall_s):
        evs = [_ev(0, "run_start", 10.0)]
        for p in range(3):
            evs.append(_period(0, 20.0 + 8 * p, p))
        if stall_s:
            evs.append(_ev(0, "stall", 50.0, step=9, age=stall_s,
                           deadline=4.0, action="exit", stacks={}))
            evs.append(_ev(0, "heartbeat", 50.0 + stall_s, step=9))
        evs.append(_ev(0, "run_end", 51.0 + stall_s, phases={},
                       anomalies=0))
        _append(tmp_path, job, 0, [json.dumps(e) for e in evs])

    mk("clean", 0.0)
    mk("clean2", 0.0)
    mk("stalled", 120.0)

    base = tmp_path / "base.json"
    cli.main(["obs", "baseline", "clean", "--log-dir", str(tmp_path),
              "--out", str(base)])
    capsys.readouterr()

    cli.main(["obs", "diff", "clean2", "--log-dir", str(tmp_path),
              "--baseline", str(base), "--fail-goodput-drop", "0.2"])
    out = capsys.readouterr().out
    assert "OK: goodput within the 20% gate" in out
    assert "goodput:" in out  # the diff table line

    with pytest.raises(SystemExit, match="goodput.*below"):
        cli.main(["obs", "diff", "stalled", "--log-dir", str(tmp_path),
                  "--baseline", str(base), "--fail-goodput-drop", "0.2"])
    capsys.readouterr()

    # a baseline without a goodput account (pre-ledger) fails loudly
    stored = json.loads(base.read_text())
    del stored["summary"]["goodput"]
    old = tmp_path / "old.json"
    old.write_text(json.dumps(stored))
    with pytest.raises(SystemExit, match="regenerate the baseline"):
        cli.main(["obs", "diff", "clean2", "--log-dir", str(tmp_path),
                  "--baseline", str(old), "--fail-goodput-drop", "0.2"])


# ---------------------------------------------------------------------------
# obs trace --http (PR-10 carry-over satellite)
# ---------------------------------------------------------------------------


def test_trace_http_serves_index_and_trace_json(tmp_path):
    from ddl_tpu.obs.trace import serve_trace_http

    job = "http"
    evs = _goodput_events(0)
    # a native request trace so /trace.json?slowest=1 resolves
    evs.append(_ev(
        0, "trace_span", 90.0, trace="reqA", span="reqA/req",
        parent=None, name="request", cat="serve", t0=88.0, t1=90.0,
        request_id="reqA", outcome="ok",
    ))
    evs.append(_ev(
        0, "trace_span", 89.0, trace="reqA", span="reqA/prefill",
        parent="reqA/req", name="prefill", cat="serve", t0=88.1,
        t1=88.4,
    ))
    _append(tmp_path, job, 0, [json.dumps(e) for e in evs])

    srv = threading.Thread(
        target=serve_trace_http,
        args=(tmp_path, job, 0),
        kwargs={"max_requests": 3},
        daemon=True,
    )
    # port 0 would be ephemeral; bind a fixed free port instead
    import socket

    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    srv = threading.Thread(
        target=serve_trace_http,
        args=(tmp_path, job, port),
        kwargs={"max_requests": 3},
        daemon=True,
    )
    srv.start()
    time.sleep(0.3)
    index = urllib.request.urlopen(
        f"http://127.0.0.1:{port}/", timeout=10
    ).read().decode()
    assert "ui.perfetto.dev/#!/?url=" in index
    assert "slowest request" in index
    assert "incident" in index

    body = urllib.request.urlopen(
        f"http://127.0.0.1:{port}/trace.json?slowest=1", timeout=10
    )
    assert body.headers["Access-Control-Allow-Origin"] == "*"
    trace = json.loads(body.read().decode())
    names = {e.get("name") for e in trace["traceEvents"]}
    assert "request" in names and "prefill" in names

    body = urllib.request.urlopen(
        f"http://127.0.0.1:{port}/trace.json?incident=0", timeout=10
    ).read().decode()
    assert json.loads(body)["traceEvents"]
    srv.join(timeout=10)


def test_trace_cli_requires_selector_or_http(tmp_path):
    from ddl_tpu import cli

    _append(tmp_path, "sel", 0,
            [json.dumps(e) for e in _goodput_events(0)])
    with pytest.raises(SystemExit, match="--http PORT"):
        cli.main(["obs", "trace", "sel", "--log-dir", str(tmp_path)])


# ---------------------------------------------------------------------------
# one-shot decode: native request trace spans (PR-10 carry-over)
# ---------------------------------------------------------------------------


def test_one_shot_decode_emits_native_request_trace(tmp_path):
    """`obs trace --request` works OUTSIDE the serve engine: the
    one-shot generator emits the request/queue/prefill/decode span
    chain, the fold's slowest-request cell selects it, and the built
    trace is Perfetto-shaped."""
    import jax

    from ddl_tpu.models.transformer import LMConfig
    from ddl_tpu.infer.decode import make_lm_generator
    from ddl_tpu.obs.events import EventWriter, read_events
    from ddl_tpu.obs.fold import fold_job
    from ddl_tpu.obs.trace import trace_job

    cfg = LMConfig(
        vocab_size=64, d_model=16, n_layers=1, n_heads=2, head_dim=8,
        d_ff=32, compute_dtype="float32",
    )
    w = EventWriter(tmp_path, "dtrace", host=0)
    run = make_lm_generator(
        cfg, prompt_len=4, max_new=3, batch=1, obs=w,
    )
    params = jax.eval_shape(lambda: None)  # placeholder; built below
    import numpy as np

    from flax import linen as nn  # noqa: F401 (import parity with decode)
    from ddl_tpu.models.transformer import TransformerLM

    model = TransformerLM(cfg)
    variables = model.init(
        jax.random.key(0), np.zeros((1, 4), np.int32)
    )
    prompt = np.arange(4, dtype=np.int32)[None, :]
    from time import perf_counter

    run(variables["params"], prompt, submitted_at=perf_counter() - 0.05)
    run(variables["params"], prompt)
    w.close()

    events = read_events(
        tmp_path / "by_job_id" / "dtrace" / "events-h000.jsonl"
    )
    spans = [e for e in events if e["kind"] == "trace_span"]
    roots = [s for s in spans if s["name"] == "request"]
    assert len(roots) == 2
    names = {s["name"] for s in spans}
    assert {"request", "prefill", "decode"} <= names
    assert "queue" in names  # first request carried submitted_at
    req = roots[0]["trace"]
    for s in spans:
        assert s["t1"] >= s["t0"]

    # the fold's slowest-request cell selects a one-shot request now
    fold = fold_job(tmp_path, "dtrace", cache=False)
    assert fold.trace_totals()["requests"] == 2
    slowest = fold.trace_totals()["slowest"][1]

    trace = trace_job(tmp_path, "dtrace", request=req, cache=False)
    got = {e["name"] for e in trace["traceEvents"] if e["ph"] == "X"}
    assert {"request", "prefill", "decode"} <= got
    trace2 = trace_job(tmp_path, "dtrace", slowest=True, cache=False)
    assert trace2["otherData"]["trace"] == f"request {slowest}"

    # decode events carry the request id for cross-referencing
    decs = [e for e in events if e["kind"] == "decode"]
    assert all(e.get("request_id") for e in decs)


def test_decode_trace_sampling_is_deterministic(tmp_path, monkeypatch):
    monkeypatch.setenv("DDL_OBS_TRACE_SAMPLE", "2")
    import jax
    import numpy as np

    from ddl_tpu.models.transformer import LMConfig, TransformerLM
    from ddl_tpu.infer.decode import make_lm_generator
    from ddl_tpu.obs.events import EventWriter, read_events

    cfg = LMConfig(
        vocab_size=64, d_model=16, n_layers=1, n_heads=2, head_dim=8,
        d_ff=32, compute_dtype="float32",
    )
    w = EventWriter(tmp_path, "dsamp", host=0)
    run = make_lm_generator(cfg, prompt_len=4, max_new=2, batch=1, obs=w)
    model = TransformerLM(cfg)
    variables = model.init(jax.random.key(0), np.zeros((1, 4), np.int32))
    prompt = np.arange(4, dtype=np.int32)[None, :]
    for _ in range(4):
        run(variables["params"], prompt)
    w.close()
    events = read_events(
        tmp_path / "by_job_id" / "dsamp" / "events-h000.jsonl"
    )
    roots = [
        e for e in events
        if e["kind"] == "trace_span" and e["name"] == "request"
    ]
    assert len(roots) == 2  # requests 0 and 2 of 4
    # decode latency events are NOT sampled
    assert len([e for e in events if e["kind"] == "decode"]) == 4


# ---------------------------------------------------------------------------
# supervised preempt e2e: restart gap + replayed steps as badput
# ---------------------------------------------------------------------------


def _tiny_lm(tmp_path, job_id, steps, **run_overrides):
    import optax

    from ddl_tpu.models.transformer import LMConfig
    from ddl_tpu.parallel.sharding import LMMeshSpec
    from ddl_tpu.train.lm_trainer import LMRunConfig, LMTrainer

    cfg = LMConfig(
        vocab_size=256, d_model=32, n_layers=2, n_heads=4, head_dim=8,
        d_ff=64, compute_dtype="float32", remat=False,
    )
    run_kwargs = dict(
        batch=4, seq_len=16, steps=steps, job_id=job_id,
        checkpoint_dir=str(tmp_path / "ckpt"),
        log_dir=str(tmp_path / "logs"),
    )
    run_kwargs.update(run_overrides)
    run = LMRunConfig(**run_kwargs)
    return LMTrainer(cfg, LMMeshSpec(), optax.adam(1e-3), run)


def test_supervised_preempt_and_crash_show_up_as_badput(tmp_path):
    """The acceptance e2e: a supervised run that is preempted (exact
    resume) and then crashes (resume from the preemption snapshot)
    books a restart gap AND replayed steps as badput, and the account
    still sums to the wall clock; warm == cold on the real stream."""
    import ddl_tpu.obs.steptrace as st_mod
    from ddl_tpu.supervisor import EXIT_PREEMPTED, Supervisor
    from ddl_tpu.utils import faultinject

    job = "lm-goodput-e2e"
    total_steps = 8

    def attempt(restart_index):
        # in-process supervision: thread the relaunch decision stamp +
        # reset the once-per-process restart-latency consumption the
        # way a real child process would see them
        st_mod._relaunch_consumed = False
        if sup.last_relaunch_ts and restart_index > 0:
            os.environ["DDL_RELAUNCH_TS"] = repr(sup.last_relaunch_ts)
        else:
            os.environ.pop("DDL_RELAUNCH_TS", None)
        if restart_index == 0:
            faultinject.activate("preempt@step:3")
        elif restart_index == 1:
            faultinject.activate("crash@step:6")
        else:
            faultinject.deactivate()
        try:
            t = _tiny_lm(
                tmp_path, job, steps=total_steps,
                save_every=10 ** 9, log_every=2,
            )
            t.train()
        except faultinject.InjectedCrash:
            return 1
        finally:
            faultinject.deactivate()
        if t.preempted:
            return EXIT_PREEMPTED
        assert int(t.state.step) == total_steps
        return 0

    sup = Supervisor(attempt, max_restarts=3, sleep=lambda d: None,
                     log=lambda m: None)
    try:
        assert sup.run() == 0
    finally:
        os.environ.pop("DDL_RELAUNCH_TS", None)
    assert sup.preemptions == 1 and sup.crashes == 1

    from ddl_tpu.obs.fold import fold_job
    from ddl_tpu.obs.goodput import ledger_from_fold

    logs = tmp_path / "logs"
    ledger = ledger_from_fold(fold_job(logs, job, cache=False))
    _assert_exhaustive(ledger)
    job_row = ledger["job"]["seconds"]
    # the two dead windows between attempts are restart gap, and the
    # crash relaunch replayed the steps since the preemption snapshot
    assert job_row["restart_gap"] > 0.0
    assert job_row["rolled_back"] > 0.0
    assert job_row["checkpoint"] > 0.0  # startup restores were stamped
    assert job_row["productive"] > 0.0
    # the restores actually emitted cursors
    from ddl_tpu.obs.events import read_events

    events = read_events(logs / "by_job_id" / job / "events-h000.jsonl")
    restores = [e for e in events if e["kind"] == "snapshot_restore"]
    assert len(restores) == 2
    assert all("period" in e and "offset" in e for e in restores)
    rls = [e for e in events if e["kind"] == "restart_latency"]
    assert len(rls) == 2

    # the real stream renders warm == cold
    warm_g, warm_s, _ = _render_all(logs, job, cache=True)
    cold_g, cold_s, _ = _render_all(logs, job, cache=False)
    assert warm_g == cold_g and warm_s == cold_s
