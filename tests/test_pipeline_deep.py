"""Deeper pipelines (4 stages) and bfloat16 compute through the schedule.

The reference only ever ran 2 stages successfully (its 4-stage attempt hit
FX-split failures and a time regression, ``debug.py:9-29``); constructive
block-boundary staging has no such limitation, so 4 stages must work and
stay numerically correct.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from ddl_tpu.config import ModelConfig
from ddl_tpu.models import build_stages, stage_boundary_shapes
from ddl_tpu.parallel.mesh import MeshSpec, build_mesh
from ddl_tpu.parallel.pipeline import make_pipeline_step_fns
from ddl_tpu.train.state import create_train_state

IMG = 16
B = 8


@pytest.fixture(scope="module")
def cfg4():
    return ModelConfig(
        growth_rate=4,
        block_config=(1, 1, 1, 1),
        num_init_features=8,
        bn_size=2,
        num_classes=5,
        split_blocks=(1, 2, 3),
        compute_dtype="float32",
        remat=False,
    )


def test_four_stage_pipeline_matches_sequential(cfg4, batch_data=None):
    from tests.test_parallel import sequential_reference_step, _assert_tree_close

    # 32px: the 4-block net halves spatial dims 5 times (stem x2 + 3
    # transitions), so 16px would collapse to 0x0 before the last block.
    img = 32
    stages = build_stages(cfg4)
    assert len(stages) == 4
    tx = optax.sgd(0.1)
    state = create_train_state(stages, tx, jax.random.key(0), img)
    mesh = build_mesh(MeshSpec(2, 4))
    fns = make_pipeline_step_fns(
        stages,
        tx,
        mesh,
        jnp.float32,
        num_microbatches=2,
        boundary_shapes=stage_boundary_shapes(cfg4, img),
        num_classes=5,
        remat=False,
    )
    rng = np.random.default_rng(0)
    images = rng.integers(0, 255, (B, img, img, 3)).astype(np.uint8)
    labels = rng.integers(0, 5, (B,)).astype(np.int32)
    clone = jax.tree.map(jnp.copy, state)
    new_state, loss, preds = fns.train(clone, images, labels)
    ref_params, ref_stats, ref_loss, ref_preds = sequential_reference_step(
        stages, tx, state, images, labels, M=2, D=2
    )
    assert float(loss) == pytest.approx(ref_loss, abs=1e-5)
    np.testing.assert_array_equal(np.asarray(preds), ref_preds)
    # fp32 reduction-order noise across a 4-deep pipeline: ~4e-5 worst case
    _assert_tree_close(new_state.params, ref_params, atol=1e-4)


def test_four_stage_1f1b_matches_gpipe(cfg4):
    """1F1B on a 4-deep pipeline (warmup/steady/cooldown phases all
    exercised: M=4 microbatches, ring depths 7/5/3/1 clamped to 4)."""
    img = 32
    stages = build_stages(cfg4)
    tx = optax.sgd(0.1)
    state = create_train_state(stages, tx, jax.random.key(0), img)
    mesh = build_mesh(MeshSpec(1, 4))
    kwargs = dict(
        tx=tx,
        mesh=mesh,
        compute_dtype=jnp.float32,
        num_microbatches=4,
        boundary_shapes=stage_boundary_shapes(cfg4, img),
        num_classes=5,
        remat=False,
    )
    g = make_pipeline_step_fns(stages, schedule="gpipe", **kwargs)
    f = make_pipeline_step_fns(stages, schedule="1f1b", **kwargs)
    rng = np.random.default_rng(0)
    images = rng.integers(0, 255, (B, img, img, 3)).astype(np.uint8)
    labels = rng.integers(0, 5, (B,)).astype(np.int32)
    clone = lambda s: jax.tree.map(jnp.copy, s)
    sg, lg, pg = g.train(clone(state), images, labels)
    sf, lf, pf = f.train(clone(state), images, labels)
    assert float(lg) == pytest.approx(float(lf), abs=1e-6)
    np.testing.assert_array_equal(np.asarray(pg), np.asarray(pf))
    from tests.test_parallel import _assert_tree_close

    _assert_tree_close(sg.params, sf.params, atol=1e-6)


def test_bfloat16_pipeline_step(tiny_model_cfg):
    """bf16 compute dtype must run and learn-step without NaNs (the TPU MXU
    path); params stay f32."""
    import dataclasses

    cfg = dataclasses.replace(tiny_model_cfg, compute_dtype="bfloat16")
    stages = build_stages(cfg)
    tx = optax.adam(1e-3)
    state = create_train_state(stages, tx, jax.random.key(0), IMG)
    mesh = build_mesh(MeshSpec(2, 2))
    fns = make_pipeline_step_fns(
        stages,
        tx,
        mesh,
        jnp.bfloat16,
        num_microbatches=2,
        boundary_shapes=stage_boundary_shapes(cfg, IMG),
        num_classes=5,
        remat=True,
    )
    rng = np.random.default_rng(0)
    images = rng.integers(0, 255, (B, IMG, IMG, 3)).astype(np.uint8)
    labels = rng.integers(0, 5, (B,)).astype(np.int32)
    new_state, loss, _ = fns.train(state, images, labels)
    assert np.isfinite(float(loss))
    for leaf in jax.tree.leaves(new_state.params):
        assert leaf.dtype == jnp.float32  # master weights stay f32
        assert bool(jnp.isfinite(leaf).all())


def test_bfloat16_dp_step(tiny_model_cfg):
    import dataclasses

    from ddl_tpu.train.steps import make_dp_step_fns

    cfg = dataclasses.replace(tiny_model_cfg, compute_dtype="bfloat16")
    stages = build_stages(cfg, num_stages=1)
    tx = optax.adam(1e-3)
    state = create_train_state(stages, tx, jax.random.key(0), IMG)
    fns = make_dp_step_fns(stages, tx, build_mesh(MeshSpec(4, 1)), jnp.bfloat16)
    rng = np.random.default_rng(0)
    images = rng.integers(0, 255, (B, IMG, IMG, 3)).astype(np.uint8)
    labels = rng.integers(0, 5, (B,)).astype(np.int32)
    new_state, loss, _ = fns.train(state, images, labels)
    assert np.isfinite(float(loss))
