"""Continuous-batching serving engine (`ddl_tpu/serve/`).

Host tier (no JAX): block allocator invariants, scheduler admission
order / retire-and-recycle / watermarks, shed-policy determinism, the
ServingStats falsy-0.0 regression, the incremental tail-cursor cache,
and the new `obs diff` serving gates over synthetic streams.

Device tier (CPU JAX): paged-pool write/gather equivalence against a
contiguous reference, and the acceptance e2e — N concurrent clients
through the engine produce bit-identical tokens to N sequential
`make_lm_generator` runs (greedy, sampled, and int8-KV), with
recompiles bounded by the bucket grid and counted via obs events.
"""

import json
import os
import sys
import time

import numpy as np
import pytest


# ---------------------------------------------------------------------------
# host tier: geometry helpers
# ---------------------------------------------------------------------------


def test_blocks_for_and_buckets():
    from ddl_tpu.serve.engine import pow2_at_least, pow2_at_most, prompt_bucket
    from ddl_tpu.serve.kv_pool import blocks_for

    assert blocks_for(1, 8) == 1
    assert blocks_for(8, 8) == 1
    assert blocks_for(9, 8) == 2
    with pytest.raises(ValueError):
        blocks_for(0, 8)

    # smallest power-of-two multiple of block_size >= prompt_len
    assert prompt_bucket(1, 8) == 8
    assert prompt_bucket(8, 8) == 8
    assert prompt_bucket(9, 8) == 16
    assert prompt_bucket(17, 8) == 32
    assert prompt_bucket(5, 4) == 8
    with pytest.raises(ValueError):
        prompt_bucket(0, 8)

    assert [pow2_at_most(n) for n in (1, 2, 3, 7, 8, 9)] == [1, 2, 2, 4, 8, 8]
    assert [pow2_at_least(n) for n in (1, 2, 3, 7, 8, 9)] == [
        1, 2, 4, 8, 8, 16,
    ]


# ---------------------------------------------------------------------------
# host tier: block allocator
# ---------------------------------------------------------------------------


def test_allocator_invariants():
    from ddl_tpu.serve.kv_pool import BlockAllocator, PoolExhausted

    a = BlockAllocator(8, 4)
    x = a.alloc(3)
    y = a.alloc(2)
    # a block is never handed out twice
    assert len(set(x) & set(y)) == 0
    assert a.free_blocks + a.used_blocks == 8
    assert a.high_water == 5
    assert not a.can_alloc(4)
    with pytest.raises(PoolExhausted):
        a.alloc(4)
    a.free(x)
    assert a.free_blocks == 6
    # freeing twice is a bookkeeping bug, loudly
    with pytest.raises(ValueError):
        a.free(x)
    # lowest-id-first: recycled low ids come back before fresh high ids
    z = a.alloc(3)
    assert z == sorted(z) == [0, 1, 2]
    assert a.free_blocks + a.used_blocks == 8
    assert a.high_water == 5  # peak, not current


def test_allocator_fragmentation_and_compaction():
    from ddl_tpu.serve.kv_pool import BlockAllocator

    a = BlockAllocator(8, 4)
    x = a.alloc(2)  # [0, 1]
    y = a.alloc(2)  # [2, 3]
    z = a.alloc(2)  # [4, 5]
    assert a.fragmentation() == 0.0
    assert a.compaction_plan() is None
    a.free(y)
    # live span [0, 5] holds 4 blocks -> 1/3 holes
    assert a.fragmentation() == pytest.approx(1 - 4 / 6)
    plan = a.compaction_plan()
    # packs live blocks to lowest ids, preserving relative order
    assert plan == {4: 2, 5: 3}
    a.commit_plan(plan)
    assert a.fragmentation() == 0.0
    assert sorted(a._refs) == [0, 1, 2, 3]
    assert all(a.refcount(b) == 1 for b in range(4))
    assert a.free_blocks == 4
    del x, z


# ---------------------------------------------------------------------------
# host tier: scheduler
# ---------------------------------------------------------------------------


def _req(rid, prompt_len=8, max_new=4, **kw):
    from ddl_tpu.serve.scheduler import Request

    return Request(
        id=rid, prompt=np.zeros(prompt_len, np.int32), max_new=max_new, **kw
    )


def test_scheduler_admission_order_and_retire_recycle():
    from ddl_tpu.serve.kv_pool import BlockAllocator
    from ddl_tpu.serve.scheduler import ContinuousScheduler

    alloc = BlockAllocator(8, 8)
    s = ContinuousScheduler(alloc, max_batch=2, max_blocks_per_seq=4)
    a = s.try_admit(_req("a", 8, 8))   # 2 blocks
    b = s.try_admit(_req("b", 8, 8))   # 2 blocks
    assert (a.lane, b.lane) == (0, 1)  # lanes bound in admission order
    assert s.try_admit(_req("c")) is None  # no free lane
    # retire-and-recycle: blocks return and the freed lane rebinds
    freed = set(a.block_ids)
    s.retire(a.lane)
    assert alloc.free_blocks == 6
    c = s.try_admit(_req("c", 8, 8))
    assert c.lane == 0
    assert set(c.block_ids) == freed  # lowest-first recycles the hole
    s.retire(0)
    with pytest.raises(ValueError):
        s.retire(0)  # retiring an idle lane is a bookkeeping bug
    s.retire(1)
    assert alloc.used_blocks == 0


def test_scheduler_watermark_and_fits_ever():
    from ddl_tpu.serve.kv_pool import BlockAllocator
    from ddl_tpu.serve.scheduler import ContinuousScheduler

    alloc = BlockAllocator(4, 8)
    s = ContinuousScheduler(
        alloc, max_batch=4, max_blocks_per_seq=4, min_free_blocks=2
    )
    # needs 1 block but must leave 2 free: ok at 4 free, refused at 2
    assert s.can_admit(_req("a", 4, 4))
    s.try_admit(_req("a", 8, 8))  # 2 blocks -> 2 free
    assert not s.can_admit(_req("b", 4, 4))
    assert s.try_admit(_req("b", 4, 4)) is None
    # oversize request: impossible EVER, not merely now
    big = _req("big", 30, 8)  # 37 rows -> 5 blocks > max_blocks_per_seq
    assert not s.fits_ever(big)
    with pytest.raises(ValueError):
        s.try_admit(big)
    # fits the table but never the pool once the watermark is held
    # back: queueing it would livelock the drain loop (regression)
    alloc2 = BlockAllocator(4, 8)
    s2 = ContinuousScheduler(
        alloc2, max_batch=4, max_blocks_per_seq=8, min_free_blocks=2
    )
    never = _req("never", 20, 8)  # 28 rows -> 4 blocks; 4+2 > pool of 4
    assert not s2.fits_ever(never)
    assert s2.fits_ever(_req("ok", 8, 8))  # 2 blocks: 2+2 <= 4


def test_shed_policies_deterministic():
    from ddl_tpu.serve.admission import AdmissionController

    def drive(policy):
        sheds = []
        c = AdmissionController(
            max_queue=2, policy=policy,
            on_shed=lambda r, reason: sheds.append((r.id, reason)),
        )
        outcomes = [c.offer(_req(f"r{i}")) for i in range(4)]
        outcomes.append(c.offer(_req("huge"), fits_ever=False))
        return outcomes, sheds, [r.id for r in c.queue]

    # reject: new arrivals turned away, queue keeps the oldest
    out, sheds, q = drive("reject")
    assert out == ["queued", "queued", "rejected", "rejected", "rejected"]
    assert sheds == [
        ("r2", "queue_full"), ("r3", "queue_full"), ("huge", "too_large"),
    ]
    assert q == ["r0", "r1"]
    # shed_oldest: freshest-first under overload
    out, sheds, q = drive("shed_oldest")
    assert out == [
        "queued", "queued", "queued_shed_oldest", "queued_shed_oldest",
        "rejected",
    ]
    assert sheds == [
        ("r0", "queue_full"), ("r1", "queue_full"), ("huge", "too_large"),
    ]
    assert q == ["r2", "r3"]
    # determinism: the same pressure pattern sheds the same requests
    assert drive("shed_oldest") == drive("shed_oldest")


# ---------------------------------------------------------------------------
# host tier: ServingStats falsy-zero regression + serving gates
# ---------------------------------------------------------------------------


def _decode_event(ts, **kw):
    e = dict(
        kind="decode", ts=ts, request_id="r", prompt_len=8, new_tokens=4,
        batch=1, dur=0.1, tok_per_s=40.0, warm=True, chips=2,
    )
    e.update(kw)
    return e


def test_serving_stats_zero_values_are_present():
    """queue_delay_s=0.0 / ttft_s=0.0 are measurements, not gaps — the
    falsy-drop regression this PR pins down."""
    from ddl_tpu.obs.serving import ServingStats

    events = [
        _decode_event(10.0, queue_delay=0.0, ttft=0.0),
        _decode_event(10.2, queue_delay=0.0, ttft=0.0),
        _decode_event(10.4, queue_delay=0.5, ttft=0.25),
    ]
    s = ServingStats.from_events(events).summary()
    pct = s["percentiles"]
    assert pct["queue_delay_s"]["count"] == 3
    assert pct["ttft_s"]["count"] == 3
    assert pct["queue_delay_s"]["p50"] == 0.0
    assert pct["ttft_s"]["p50"] == 0.0
    # warm-span aggregate: 12 warm tokens over [9.9, 10.4]
    assert s["agg_tok_per_s"] == pytest.approx(12 / 0.5)
    assert s["chips"] == 2
    assert s["agg_tok_per_s_per_chip"] == pytest.approx(12 / 0.5 / 2)


def test_agg_spans_per_engine_not_global():
    """A CI job stream holds a decode smoke and a serve-bench smoke
    minutes apart; the aggregate must sum per-engine activity windows,
    not stretch one span across the idle gap (regression: the gate
    would otherwise move with test ordering, not serving perf)."""
    from ddl_tpu.obs.serving import ServingStats

    events = [
        _decode_event(10.0),                      # one-shot decode
        _decode_event(10.1),                      # span [9.9, 10.1]
        _decode_event(300.0, engine="serve"),     # serve-bench, 5 min
        _decode_event(300.3, engine="serve"),     # later: [299.9, 300.3]
    ]
    s = ServingStats.from_events(events).summary()
    # 16 warm tokens over 0.2s + 0.4s of ACTIVITY, not over ~290s
    assert s["agg_tok_per_s"] == pytest.approx(16 / 0.6)
    # round-trips through the cursor sidecar state
    rt = ServingStats.from_state(ServingStats.from_events(events).state_dict())
    assert rt.summary() == s


def test_summarize_mean_rate_zero_not_dropped():
    """A cold-only stream whose tok_per_s is exactly 0.0 must still
    populate the legacy mean (absence, not falsiness, drops it)."""
    from ddl_tpu.obs.report import summarize_run

    events = [
        _decode_event(1.0, warm=False, tok_per_s=0.0),
        {"kind": "period", "period": 0},
    ]
    s = summarize_run(events)
    assert s["decode"]["mean_tok_per_s"] == 0.0


def _write_stream(log_dir, job, events, host=0):
    job_dir = log_dir / "by_job_id" / job  # report._job_dir layout
    job_dir.mkdir(parents=True, exist_ok=True)
    path = job_dir / f"events-h{host:03d}.jsonl"
    with open(path, "a") as f:
        for e in events:
            f.write(json.dumps(e) + "\n")
    return path


def test_cursor_incremental_matches_scratch(tmp_path):
    """The tail-cursor cache folds only appended bytes and matches a
    from-scratch rebuild exactly (same reservoir, same percentiles)."""
    from ddl_tpu.obs.cursor import CACHE_NAME, incremental_serving_stats

    job = tmp_path / "by_job_id" / "j1"
    rng = np.random.default_rng(0)
    evs = [
        _decode_event(float(i), ttft=float(rng.exponential(0.1)),
                      queue_delay=float(rng.exponential(0.05)))
        for i in range(40)
    ]
    _write_stream(tmp_path, "j1", evs[:25])
    s1 = incremental_serving_stats(tmp_path, "j1")
    assert s1.requests == 25
    assert (job / CACHE_NAME).exists()
    _write_stream(tmp_path, "j1", evs[25:])  # append the tail
    s2 = incremental_serving_stats(tmp_path, "j1")
    ref = incremental_serving_stats(tmp_path, "j1", cache=False)
    assert s2.requests == ref.requests == 40
    assert s2.summary() == ref.summary()
    # the cursor consumed the whole file: a third call reads 0 new bytes
    cursor = json.loads((job / CACHE_NAME).read_text())
    size = (job / "events-h000.jsonl").stat().st_size
    assert cursor["files"]["events-h000.jsonl"] == size


def test_cursor_torn_line_and_truncation(tmp_path):
    from ddl_tpu.obs.cursor import CACHE_NAME, incremental_serving_stats

    job = tmp_path / "by_job_id" / "j2"
    path = _write_stream(
        tmp_path, "j2", [_decode_event(1.0), _decode_event(2.0)]
    )
    # torn final line: stays un-consumed until completed
    with open(path, "a") as f:
        f.write('{"kind": "decode", "ts": 3.0, "new_')
    s = incremental_serving_stats(tmp_path, "j2")
    assert s.requests == 2
    with open(path, "a") as f:
        f.write('tokens": 4, "warm": true, "batch": 1}\n')
    s = incremental_serving_stats(tmp_path, "j2")
    assert s.requests == 3
    # truncation below the cursor: clean rebuild, never double-count
    with open(path, "w") as f:
        f.write(json.dumps(_decode_event(9.0)) + "\n")
    s = incremental_serving_stats(tmp_path, "j2")
    assert s.requests == 1
    assert (job / CACHE_NAME).exists()


def test_cursor_recreated_stream_rebuilds(tmp_path):
    """A stream deleted and re-created under the same name (re-used job
    id) must rebuild, not fold on top of the old run's accumulators —
    even when the new file is LARGER than the old cursor, where a pure
    size check passes (regression: head-fingerprint guard)."""
    from ddl_tpu.obs.cursor import incremental_serving_stats

    path = _write_stream(tmp_path, "j3", [_decode_event(1.0)])
    assert incremental_serving_stats(tmp_path, "j3").requests == 1
    # re-create, same name, MORE events than the old cursor consumed
    path.unlink()
    _write_stream(
        tmp_path, "j3", [_decode_event(float(t)) for t in range(5, 9)]
    )
    s = incremental_serving_stats(tmp_path, "j3")
    ref = incremental_serving_stats(tmp_path, "j3", cache=False)
    assert s.requests == ref.requests == 4  # not 1 + 4
    assert s.summary() == ref.summary()
    # a tracked stream that disappeared outright also rebuilds: the
    # surviving host's events must not ride on stale accumulators
    _write_stream(tmp_path, "j4", [_decode_event(1.0)], host=0)
    extra = _write_stream(tmp_path, "j4", [_decode_event(2.0)], host=1)
    assert incremental_serving_stats(tmp_path, "j4").requests == 2
    extra.unlink()
    assert incremental_serving_stats(tmp_path, "j4").requests == 1


def test_cursor_corrupt_sidecar_rebuilds(tmp_path):
    """A JSON-valid sidecar with the wrong inner shape must be
    discarded and rebuilt, not crash every summarize until an operator
    deletes it by hand (the module's stated contract)."""
    from ddl_tpu.obs.cursor import (
        CACHE_NAME, VERSION, incremental_serving_stats,
    )

    job = tmp_path / "by_job_id" / "j5"
    _write_stream(tmp_path, "j5", [_decode_event(1.0), _decode_event(2.0)])
    assert incremental_serving_stats(tmp_path, "j5").requests == 2
    (job / CACHE_NAME).write_text(json.dumps({
        "version": VERSION, "capacity": 4096, "files": {},
    }))  # passes _load_cache, breaks the stats restore
    s = incremental_serving_stats(tmp_path, "j5")
    assert s.requests == 2
    # and the rebuild repaired the sidecar in place
    assert incremental_serving_stats(tmp_path, "j5").requests == 2


def _run_obs(argv):
    from ddl_tpu.obs import report

    old = sys.argv
    sys.argv = ["obs"] + argv
    try:
        report.main()
    finally:
        sys.argv = old


def test_obs_diff_gates_ttft_and_aggregate(tmp_path, capsys):
    """`obs diff --fail-slowdown` gates p99 TTFT inflation and aggregate
    tokens/s/chip drops (the two serve-bench acceptance gates)."""
    evs = [
        _decode_event(
            10.0 + 0.1 * i, ttft=0.01 + 0.001 * i, queue_delay=0.0,
        )
        for i in range(20)
    ] + [{"kind": "period", "period": 0, "steps_per_s": 10.0, "steps": 1}]
    _write_stream(tmp_path, "serve", evs)
    base = tmp_path / "base.json"
    _run_obs([
        "baseline", "serve", "--log-dir", str(tmp_path), "--out", str(base),
    ])
    # run vs its own baseline: all gates pass, and say which ran
    _run_obs([
        "diff", "serve", "--log-dir", str(tmp_path),
        "--baseline", str(base), "--fail-slowdown", "0.5",
    ])
    ok_line = capsys.readouterr().out
    assert "OK" in ok_line
    # doctor the baseline: a much better p99 TTFT -> current run fails
    doctored = json.loads(base.read_text())
    doctored["summary"]["decode"]["percentiles"]["ttft_s"]["p99"] = 1e-5
    bad = tmp_path / "ttft.json"
    bad.write_text(json.dumps(doctored))
    with pytest.raises(SystemExit, match="p99 TTFT"):
        _run_obs([
            "diff", "serve", "--log-dir", str(tmp_path),
            "--baseline", str(bad), "--fail-slowdown", "0.5",
        ])
    # a much better aggregate tokens/s/chip -> current run fails
    doctored = json.loads(base.read_text())
    d = doctored["summary"]["decode"]
    d["agg_tok_per_s_per_chip"] = d["agg_tok_per_s_per_chip"] * 10
    bad = tmp_path / "agg.json"
    bad.write_text(json.dumps(doctored))
    with pytest.raises(SystemExit, match="tok/s/chip"):
        _run_obs([
            "diff", "serve", "--log-dir", str(tmp_path),
            "--baseline", str(bad), "--fail-slowdown", "0.5",
        ])


# ---------------------------------------------------------------------------
# device tier: paged pool vs contiguous reference
# ---------------------------------------------------------------------------


def _tiny_cfg(**kw):
    from ddl_tpu.models.transformer import LMConfig

    base = dict(
        vocab_size=256, d_model=64, n_layers=2, n_heads=8, head_dim=8,
        d_ff=256, compute_dtype="float32",
    )
    base.update(kw)
    return LMConfig(**base)


@pytest.fixture(scope="module")
def lm():
    """Tiny LM params shared by every engine test in this module."""
    import flax.linen as nn
    import jax
    import jax.numpy as jnp

    from ddl_tpu.models.transformer import TransformerLM
    from ddl_tpu.parallel.sharding import LMMeshSpec

    cfg = _tiny_cfg()
    params = nn.meta.unbox(
        TransformerLM(cfg, None).init(
            jax.random.key(0), jnp.zeros((1, 8), jnp.int32)
        )["params"]
    )
    return cfg, params, LMMeshSpec()


@pytest.mark.parametrize("quant", [False, True])
def test_kv_pool_write_gather_roundtrip(quant):
    """pool_write_prefill + pool_write_token + pool_gather reproduce a
    contiguous cache exactly, and cache_write_token lands each row at
    the same gathered index a fresh gather would show."""
    import jax
    import jax.numpy as jnp

    from ddl_tpu.ops.quant import QuantKV
    from ddl_tpu.serve.kv_pool import (
        cache_write_token,
        init_kv_pool,
        pool_gather,
        pool_write_prefill,
        pool_write_token,
    )

    cfg = _tiny_cfg(n_layers=1)
    bs, nb = 4, 8
    pools = init_kv_pool(cfg, nb, bs, quant=quant)
    pool = pools[0]
    hkv, dh = cfg.kv_heads, cfg.head_dim
    rng = np.random.default_rng(1)

    # one request: 6 prompt rows over blocks [2, 5], then 2 decoded rows
    prompt_k = jnp.asarray(rng.normal(size=(1, 8, hkv * dh)), jnp.float32)
    prompt_v = jnp.asarray(rng.normal(size=(1, 8, hkv * dh)), jnp.float32)
    if quant:
        from ddl_tpu.ops.quant import kv_unfuse, quantize_q8

        def fuse_cache(k4, v4):
            kq, ks = quantize_q8(k4)
            vq, vs = quantize_q8(v4)
            b, t = k4.shape[:2]
            return QuantKV(
                kq.reshape(b, t, -1), ks[..., 0].transpose(0, 2, 1),
                vq.reshape(b, t, -1), vs[..., 0].transpose(0, 2, 1),
            )

        cache = fuse_cache(
            prompt_k.reshape(1, 8, hkv, dh), prompt_v.reshape(1, 8, hkv, dh)
        )
        del kv_unfuse
    else:
        cache = (prompt_k, prompt_v)
    ids = jnp.asarray([2, 5], jnp.int32)
    pool = pool_write_prefill(pool, cache, ids)

    tables = jnp.asarray([[2, 5]], jnp.int32)
    gathered = pool_gather(pool, tables)
    if quant:
        ref = cache.kq[0]
        got = gathered.kq[0]
    else:
        ref, got = prompt_k[0], gathered[0][0]
    np.testing.assert_array_equal(np.asarray(got), np.asarray(ref))

    # append one decoded row at length 6 (block 5, slot 2) both ways
    k_new = jnp.asarray(rng.normal(size=(1, 1, hkv, dh)), jnp.float32)
    v_new = jnp.asarray(rng.normal(size=(1, 1, hkv, dh)), jnp.float32)
    pool2 = pool_write_token(
        pool, k_new, v_new, jnp.asarray([5]), jnp.asarray([2])
    )
    fresh = pool_gather(pool2, tables)
    appended = cache_write_token(gathered, k_new, v_new, jnp.asarray([6]))
    f1, f2 = jax.tree_util.tree_leaves(fresh), jax.tree_util.tree_leaves(
        appended
    )
    for a, b in zip(f1, f2):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    # idle-lane drop: out-of-range block id leaves the pool untouched
    pool3 = pool_write_token(
        pool2, k_new, v_new, jnp.asarray([nb]), jnp.asarray([0])
    )
    for a, b in zip(
        jax.tree_util.tree_leaves(pool2), jax.tree_util.tree_leaves(pool3)
    ):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


# ---------------------------------------------------------------------------
# device tier: the engine e2e (acceptance)
# ---------------------------------------------------------------------------


def _sequential_tokens(cfg, spec, params, clients, seed, **gen_kw):
    import jax
    import jax.numpy as jnp

    from ddl_tpu.infer.decode import make_lm_generator

    out, gens = {}, {}
    for cid, prompt, mn in clients:
        key = (len(prompt), mn)
        if key not in gens:
            gens[key] = make_lm_generator(
                cfg, spec, prompt_len=len(prompt), max_new=mn, batch=1,
                **gen_kw,
            )
        toks = gens[key](
            params, jnp.asarray(prompt[None, :]), jax.random.PRNGKey(seed)
        )
        out[cid] = np.asarray(toks)[0]
    return out


def _clients(n, rng, lo=5, hi=20, new_lo=4, new_hi=12):
    return [
        (
            f"c{i}",
            rng.integers(0, 256, int(rng.integers(lo, hi))).astype(np.int32),
            int(rng.integers(new_lo, new_hi)),
        )
        for i in range(n)
    ]


def test_engine_matches_sequential_decode(lm):
    """THE acceptance e2e: 8 concurrent clients, mixed prompt/output
    lengths, bit-identical to 8 one-at-a-time LMDecode runs."""
    from ddl_tpu.serve.engine import ServeEngine

    cfg, params, spec = lm
    clients = _clients(8, np.random.default_rng(7))
    eng = ServeEngine(cfg, params, spec, block_size=8, num_blocks=64,
                      max_batch=8)
    for cid, prompt, mn in clients:
        eng.submit(prompt, mn, request_id=cid, rng_seed=3)
    got = eng.run()
    want = _sequential_tokens(cfg, spec, params, clients, seed=3)
    assert set(got) == set(want)
    for cid in want:
        np.testing.assert_array_equal(got[cid], want[cid])
        assert eng.outcomes[cid] == "ok"
    assert eng.stats["completed"] == 8
    # every lane retired, every block recycled
    assert eng.allocator.used_blocks == 0
    assert not eng.busy


@pytest.mark.parametrize(
    "kw",
    [dict(temperature=0.8, top_k=17), dict(kv_quant=True)],
    ids=["sampled", "quant_kv"],
)
def test_engine_matches_sequential_variants(lm, kw):
    """Same RNG split sequence as the fused generator (sampled), and the
    int8 pool path (ops.quant.QuantKV) — still token-exact."""
    from ddl_tpu.serve.engine import ServeEngine

    cfg, params, spec = lm
    clients = _clients(4, np.random.default_rng(3))
    eng = ServeEngine(cfg, params, spec, block_size=8, num_blocks=64,
                      max_batch=4, **kw)
    for cid, prompt, mn in clients:
        eng.submit(prompt, mn, request_id=cid, rng_seed=11)
    got = eng.run()
    want = _sequential_tokens(cfg, spec, params, clients, seed=11, **kw)
    for cid in want:
        np.testing.assert_array_equal(got[cid], want[cid])


def test_engine_max_new_one(lm):
    """A request done straight out of admission (max_new=1: the
    prefill's sampled token is the whole output) must not crash the
    decode chunk-length computation or stall the batch behind it
    (regression: remaining=0 reached pow2_at_most)."""
    from ddl_tpu.serve.engine import ServeEngine

    cfg, params, spec = lm
    clients = [
        ("one", np.arange(6, dtype=np.int32), 1),
        ("few", np.arange(9, dtype=np.int32), 5),
    ]
    eng = ServeEngine(cfg, params, spec, block_size=8, num_blocks=32,
                      max_batch=4)
    for cid, prompt, mn in clients:
        eng.submit(prompt, mn, request_id=cid, rng_seed=2)
    got = eng.run()
    want = _sequential_tokens(cfg, spec, params, clients, seed=2)
    for cid in want:
        np.testing.assert_array_equal(got[cid], want[cid])
    assert len(got["one"]) == 1
    assert eng.allocator.used_blocks == 0 and not eng.busy


def test_bucket_bounded_recompiles_counted_via_obs(lm, tmp_path):
    """Prompts inside one bucket share a prefill program; admits/retires
    never rebuild the decode program; every compile is visible both in
    engine stats and in the emitted obs events."""
    from ddl_tpu.obs import EventWriter
    from ddl_tpu.obs.report import load_run
    from ddl_tpu.serve.engine import ServeEngine

    cfg, params, spec = lm
    obs = EventWriter(tmp_path, "serve-test")
    # prefix cache off: this test pins the BUCKETED full-prefill program
    # accounting, and these arange prompts share full-block prefixes
    # that would otherwise (correctly) divert admits to the chunk path
    eng = ServeEngine(cfg, params, spec, block_size=8, num_blocks=64,
                      max_batch=4, max_steps_per_dispatch=4, obs=obs,
                      prefix_cache=False)
    # lens 3..8 share bucket 8; lens 9..15 bucket 16
    clients = [
        ("a", np.arange(1, 6, dtype=np.int32), 6),    # bucket 8
        ("b", np.arange(1, 9, dtype=np.int32), 6),    # bucket 8 (shared)
        ("c", np.arange(1, 13, dtype=np.int32), 6),   # bucket 16
        ("d", np.arange(1, 4, dtype=np.int32), 6),    # bucket 8 (shared)
    ]
    for cid, prompt, mn in clients:
        eng.submit(prompt, mn, request_id=cid)
    eng.run()
    obs.close()
    assert eng.stats["prefill_compiles"] == 2  # one per bucket, not per req
    # decode grid is log x log: k in {1,2,4}, nmax in {1,2} here
    assert eng.stats["decode_compiles"] <= 6
    assert eng.stats["decode_steps"] < eng.stats["decode_compiles"] * 100

    events = load_run(tmp_path, "serve-test")
    kinds = [e["kind"] for e in events]
    assert kinds.count("serve_admit") == 4
    assert kinds.count("serve_retire") == 4
    assert kinds.count("decode") == 4
    assert "kv_pool_stats" in kinds
    admits = [e for e in events if e["kind"] == "serve_admit"]
    # the compiled flag marks exactly the first admit of each bucket
    assert [a["compiled"] for a in admits] == [True, False, True, False]
    # pool stats reach zero-used after the last retire
    last = [e for e in events if e["kind"] == "kv_pool_stats"][-1]
    assert last["used"] == 0 and last["active_lanes"] == 0
    # per-request decode events carry the serving fields, 0.0 included
    d = [e for e in events if e["kind"] == "decode"][0]
    assert d["engine"] == "serve"
    assert d["ttft"] is not None and d["queue_delay"] >= 0.0


def test_shed_under_pressure_e2e(lm, tmp_path):
    """Overload against a 1-lane engine with a 2-deep queue: admission
    control sheds deterministically, the rest complete exactly."""
    from ddl_tpu.obs import EventWriter
    from ddl_tpu.obs.report import load_run
    from ddl_tpu.serve.engine import ServeEngine

    cfg, params, spec = lm
    clients = _clients(5, np.random.default_rng(5), new_lo=3, new_hi=6)

    def drive(policy):
        obs = EventWriter(tmp_path / policy, "shed-test")
        eng = ServeEngine(
            cfg, params, spec, block_size=8, num_blocks=16, max_batch=1,
            max_queue=2, policy=policy, obs=obs,
        )
        outcomes = [
            eng.submit(prompt, mn, request_id=cid)
            for cid, prompt, mn in clients
        ]
        got = eng.run()
        obs.close()
        sheds = [
            (e["request_id"], e["reason"])
            for e in load_run(tmp_path / policy, "shed-test")
            if e["kind"] == "serve_shed"
        ]
        return outcomes, got, sheds, eng

    outcomes, got, sheds, eng = drive("reject")
    assert outcomes == ["queued"] * 2 + ["rejected"] * 3
    assert sheds == [("c2", "queue_full"), ("c3", "queue_full"),
                     ("c4", "queue_full")]
    assert sorted(got) == ["c0", "c1"]
    assert eng.stats["shed"] == 3
    want = _sequential_tokens(cfg, spec, params, clients[:2], seed=0)
    for cid in want:
        np.testing.assert_array_equal(got[cid], want[cid])

    outcomes, got, sheds, eng = drive("shed_oldest")
    assert outcomes == ["queued"] * 2 + ["queued_shed_oldest"] * 3
    # c0/c1 queued first; c2..c4 push out the oldest queued each time
    assert sheds == [("c0", "queue_full"), ("c1", "queue_full"),
                     ("c2", "queue_full")]
    assert sorted(got) == ["c3", "c4"]
    assert eng.outcomes["c0"] == "shed:queue_full"


def test_defrag_compacts_and_preserves_tokens(lm):
    """Retiring the middle request fragments the pool; defrag moves live
    blocks device-side and rewrites tables — decode continues exactly."""
    from ddl_tpu.serve.engine import ServeEngine

    cfg, params, spec = lm
    rng = np.random.default_rng(9)
    short = ("mid", rng.integers(0, 256, 8).astype(np.int32), 3)
    longs = [
        (f"l{i}", rng.integers(0, 256, 8).astype(np.int32), 12)
        for i in range(2)
    ]
    eng = ServeEngine(cfg, params, spec, block_size=4, num_blocks=16,
                      max_batch=3, max_steps_per_dispatch=1)
    eng.submit(*longs[0][1:], request_id=longs[0][0])
    eng.submit(*short[1:], request_id=short[0])
    eng.submit(*longs[1][1:], request_id=longs[1][0])
    # run until the short middle request retires, leaving a hole
    while "mid" not in eng.results:
        eng.step()
    assert eng.allocator.fragmentation() > 0.0
    moved = eng.defrag()
    assert moved
    assert eng.allocator.fragmentation() == 0.0
    eng.run()
    want = _sequential_tokens(
        cfg, spec, params, [short] + longs, seed=0
    )
    for cid in want:
        np.testing.assert_array_equal(eng.results[cid], want[cid])


def test_engine_precompile_covers_grid(lm):
    from ddl_tpu.serve.engine import ServeEngine

    cfg, params, spec = lm
    eng = ServeEngine(cfg, params, spec, block_size=8, num_blocks=32,
                      max_batch=2, max_steps_per_dispatch=2)
    counts = eng.precompile(12, 8)
    # buckets {8, 16}; ks {1, 2}; nmaxes pow2-ceil over 1..3 -> {1, 2, 4};
    # chunk grid (prefix cache on by default): (mid + final) x {8, 16}
    # at the single clamped view width (mid is reachable without a
    # chunk bound: the view clamp can split a prefix-hit tail)
    assert counts == {"prefill": 2, "decode": 6, "chunk": 4}
    # second call: everything cached
    assert eng.precompile(12, 8) == {"prefill": 0, "decode": 0, "chunk": 0}
    # a request inside the envelope then compiles NOTHING new
    eng.submit(np.arange(1, 11, dtype=np.int32), 8, request_id="r")
    eng.run()
    assert eng.stats["prefill_compiles"] == 0
    assert eng.stats["decode_compiles"] == 0


def test_engine_sharded_mesh_smoke(lm):
    """data=2/model=2 sim mesh: the contract-probed sharded program
    actually runs and retires (numerics covered by the 1-device
    exactness tests; resharded reductions may round differently)."""
    import jax

    from ddl_tpu.parallel.sharding import LMMeshSpec
    from ddl_tpu.serve.engine import ServeEngine

    if len(jax.devices()) < 4:
        pytest.skip("needs 4 sim devices")
    cfg, params, _ = lm
    eng = ServeEngine(
        cfg, params, LMMeshSpec(data=2, model=2), block_size=8,
        num_blocks=32, max_batch=4,
    )
    for i in range(4):
        eng.submit(np.arange(1, 9, dtype=np.int32), 5, request_id=f"c{i}")
    got = eng.run()
    assert sorted(got) == [f"c{i}" for i in range(4)]
    assert all(len(v) == 5 for v in got.values())
    assert eng.allocator.used_blocks == 0


def test_serve_bench_cli_report_and_obs(lm, tmp_path, capsys):
    """serve-bench end-to-end at toy scale: the report renders, the obs
    stream round-trips through `obs summarize`, and warm percentiles
    include a real TTFT."""
    from ddl_tpu.serve import bench

    log_dir = tmp_path / "logs"
    # fixed lengths + 2 lanes: wave 1 pays every compile (cold), the
    # following 3 waves reuse the programs -> warm percentiles without
    # the (slow) full-grid precompile
    bench.main([
        "--clients", "8", "--prompt-len", "8", "--max-new", "4",
        "--block-size", "8", "--num-blocks", "32", "--max-batch", "2",
        "--steps-per-dispatch", "4", "--no-warmup",
        "--obs-log-dir", str(log_dir), "--job-id", "sb-test",
    ])
    out = capsys.readouterr().out
    assert "== serve-bench report ==" in out
    assert "completed: 8" in out
    assert "aggregate:" in out
    assert "-- percentiles (warm requests) --" in out
    _run_obs(["summarize", "sb-test", "--log-dir", str(log_dir)])
    out = capsys.readouterr().out
    assert "decode: 8 requests" in out
    assert "ttft_s" in out
    assert "serving aggregate:" in out


@pytest.mark.skipif(
    not os.environ.get("DDL_SERVE_PERF"),
    reason="perf acceptance: set DDL_SERVE_PERF=1 (the verify skill "
    "serve-bench smoke); wall-clock sensitive, excluded from tier-1",
)
def test_serve_bench_beats_sequential(capsys):
    """Acceptance: at a weight-streaming-bound size the continuous batch
    beats one-request-at-a-time throughput at equal settings."""
    from ddl_tpu.serve import bench

    bench.main([
        "--clients", "8", "--prompt-len", "8:24", "--max-new", "16:32",
        "--block-size", "8", "--num-blocks", "64",
        "--d-model", "512", "--layers", "2", "--heads", "8",
        "--compare-sequential",
    ])
    out = capsys.readouterr().out
    line = [l for l in out.splitlines() if "sequential baseline" in l][0]
    ratio = float(line.rsplit("x", 1)[1])
    assert ratio > 1.0, line


def test_warmup_excluded_from_stats(lm):
    from ddl_tpu.serve.engine import ServeEngine

    cfg, params, spec = lm
    eng = ServeEngine(cfg, params, spec, block_size=8, num_blocks=32,
                      max_batch=2)
    eng.warmup(8, max_new=2)
    assert eng.stats["submitted"] == 0
    assert eng.stats["completed"] == 0
    assert "_warmup" not in eng.results
    assert all(r["request_id"] != "_warmup" for r in eng.request_log)
    # warmed bucket serves without a NEW prefill compile (the warmup's
    # own compile stays counted — it is a real compile)
    before = eng.stats["prefill_compiles"]
    eng.submit(np.arange(1, 9, dtype=np.int32), 3, request_id="r")
    eng.run()
    assert eng.stats["prefill_compiles"] == before


def test_request_log_feeds_serving_stats(lm):
    """The engine's in-memory request log is event-shaped: ServingStats
    builds the same percentile table obs summarize would."""
    from ddl_tpu.obs.serving import ServingStats
    from ddl_tpu.serve.engine import ServeEngine

    cfg, params, spec = lm
    # prefix cache off: the three IDENTICAL prompts would (correctly)
    # hit the cache and run the CoW recompute path, whose chunk-program
    # compile cold-marks request 2 — this test wants 3 warm full
    # prefills feeding the stats
    eng = ServeEngine(cfg, params, spec, block_size=8, num_blocks=32,
                      max_batch=2, prefix_cache=False)
    # precompiled engine: every request runs warm (compile detection is
    # per executable, so un-warmed second-signature compiles would
    # otherwise cold-mark trailing requests too)
    eng.precompile(8, 4)
    t0 = time.perf_counter()
    for i in range(3):
        eng.submit(np.arange(1, 9, dtype=np.int32), 4,
                   request_id=f"c{i}", submitted_at=t0)
    eng.run()
    s = ServingStats.from_events(eng.request_log).summary()
    assert s["requests"] == 3
    assert s["cold"] == 0
    pct = s["percentiles"]
    assert pct["ttft_s"]["count"] == 3
    assert pct["queue_delay_s"]["count"] == 3


# ---------------------------------------------------------------------------
# device tier: drain-and-reshard (elastic serving)
# ---------------------------------------------------------------------------


def test_drain_tapers_active_and_sheds_queued(lm, tmp_path):
    """drain(): admission closes, queued requests shed tenant-tagged,
    the in-flight lane finishes bit-exact through the normal loop, and
    late submits are rejected at the door."""
    from ddl_tpu.obs import EventWriter
    from ddl_tpu.obs.report import load_run
    from ddl_tpu.serve.engine import ServeEngine

    cfg, params, spec = lm
    clients = _clients(3, np.random.default_rng(9), new_lo=3, new_hi=6)
    obs = EventWriter(tmp_path, "drain-test")
    eng = ServeEngine(cfg, params, spec, block_size=8, num_blocks=16,
                      max_batch=1, max_queue=4, obs=obs)
    for i, (cid, prompt, mn) in enumerate(clients):
        assert eng.submit(prompt, mn, request_id=cid,
                          tenant=f"t{i}") == "queued"
    eng.step()  # admits c0 into the single lane; c1/c2 stay queued
    assert len(eng.scheduler.active()) == 1

    counts = eng.drain("preempt")
    assert counts == {"shed": 2, "parked": 0}
    assert eng.draining and eng.drain_reason == "preempt"
    assert eng.outcomes["c1"] == "shed:drained"
    assert eng.outcomes["c2"] == "shed:drained"
    # a second call is a no-op (no double-shed, no duplicate event)
    assert eng.drain("preempt") == {"shed": 0, "parked": 0}
    # admission is closed: the late arrival sheds at the door
    assert eng.submit(clients[0][1], 3, request_id="late",
                      tenant="t9") == "rejected"
    assert eng.outcomes["late"] == "shed:draining"

    got = eng.run()  # taper: the in-flight lane finishes normally
    obs.close()
    assert sorted(got) == ["c0"]
    assert eng.outcomes["c0"] == "ok"
    want = _sequential_tokens(cfg, spec, params, clients[:1], seed=0)
    np.testing.assert_array_equal(got["c0"], want["c0"])
    assert eng.allocator.used_blocks == 0 and not eng.busy
    assert eng.stats["shed"] == 3

    events = load_run(tmp_path, "drain-test")
    drains = [e for e in events if e["kind"] == "serve_drain"]
    assert len(drains) == 1
    assert drains[0]["reason"] == "preempt"
    assert drains[0]["shed"] == 2 and drains[0]["active_lanes"] == 1
    sheds = {e["request_id"]: e for e in events
             if e["kind"] == "serve_shed" and e["reason"] == "drained"}
    assert sorted(sheds) == ["c1", "c2"]
    assert sheds["c1"]["tenant"] == "t1"  # shed stays SLO-attributable


def test_drain_park_hard_stops_lanes_with_partial_outputs(lm):
    """drain(park=True): the deadline the taper cannot meet — unfinished
    lanes park NOW with partial outputs recorded, blocks recycle, and
    the engine reports not-busy."""
    from ddl_tpu.serve.engine import ServeEngine

    cfg, params, spec = lm
    eng = ServeEngine(cfg, params, spec, block_size=8, num_blocks=32,
                      max_batch=2, max_steps_per_dispatch=1)
    eng.submit(np.arange(1, 9, dtype=np.int32), 12, request_id="a")
    eng.submit(np.arange(1, 6, dtype=np.int32), 12, request_id="b")
    # run both lanes into mid-decode (well short of 12 new tokens)
    for _ in range(3):
        eng.step()
    active = eng.scheduler.active()
    assert len(active) == 2
    assert all(0 < len(s.outputs) < 12 for s in active)

    counts = eng.drain("deadline", park=True)
    assert counts["parked"] == 2
    assert eng.outcomes["a"] == "parked:deadline"
    assert eng.outcomes["b"] == "parked:deadline"
    # partial outputs preserved so a resubmission can skip them
    assert 0 < len(eng.results["a"]) < 12
    # every block recycled, nothing left to do
    assert eng.allocator.used_blocks == 0
    assert not eng.busy and not eng.step()


@pytest.mark.parametrize(
    "kw", [dict(), dict(temperature=0.8, top_k=17)], ids=["greedy", "sampled"]
)
def test_parked_requests_resume_token_identical(lm, tmp_path, kw):
    """The serving half of an elastic grow epoch: requests parked
    mid-decode by drain(park=True) resume through resume_parked() and
    complete TOKEN-IDENTICAL to decodes that were never interrupted —
    greedy trivially, sampled because the parked rng carry replays the
    exact split sequence the uninterrupted lane would have drawn."""
    from ddl_tpu.obs import EventWriter
    from ddl_tpu.obs.report import load_run
    from ddl_tpu.serve.engine import ServeEngine

    cfg, params, spec = lm
    clients = [
        ("a", np.arange(1, 9, dtype=np.int32), 12),
        ("b", np.arange(1, 6, dtype=np.int32), 12),
    ]
    obs = EventWriter(tmp_path, "resume-test")
    eng = ServeEngine(cfg, params, spec, block_size=8, num_blocks=32,
                      max_batch=2, max_steps_per_dispatch=1, obs=obs, **kw)
    for cid, prompt, mn in clients:
        eng.submit(prompt, mn, request_id=cid, rng_seed=5, tenant="t0")
    # run both lanes into mid-decode, then hard-stop for the restart
    for _ in range(4):
        eng.step()
    active = eng.scheduler.active()
    assert len(active) == 2
    assert all(0 < len(s.outputs) < 12 for s in active)
    progress = {s.request.id: len(s.outputs) for s in active}
    counts = eng.drain("scale_up", park=True)
    assert counts["parked"] == 2
    assert eng.allocator.used_blocks == 0

    # the grown pod's engine re-admits the parked work
    res = eng.resume_parked()
    assert res == {"resumed": 2, "rejected": 0}
    assert not eng.draining and eng.drain_reason is None
    got = eng.run()
    obs.close()

    want = _sequential_tokens(cfg, spec, params, clients, seed=5, **kw)
    assert sorted(got) == ["a", "b"]
    for cid, _, _mn in clients:
        np.testing.assert_array_equal(got[cid], want[cid])
        assert eng.outcomes[cid] == "ok"
    assert eng.allocator.used_blocks == 0 and not eng.busy

    # the resume is SLO-attributable: one serve_resume per request with
    # the park's progress and the remaining budget
    events = load_run(tmp_path, "resume-test")
    resumes = {e["request_id"]: e for e in events
               if e["kind"] == "serve_resume"}
    assert sorted(resumes) == ["a", "b"]
    for cid, n in progress.items():
        assert resumes[cid]["resumed_tokens"] == n
        assert resumes[cid]["remaining"] == 12 - n
        assert resumes[cid]["outcome"] != "rejected"
        assert resumes[cid]["tenant"] == "t0"


def test_preempt_guard_trips_drain_in_step(lm):
    """The supervisor-style preemption guard: step() polls it and flips
    the engine into drain without a direct drain() call."""
    from ddl_tpu.serve.engine import ServeEngine

    class Guard:
        requested = False

    cfg, params, spec = lm
    guard = Guard()
    eng = ServeEngine(cfg, params, spec, block_size=8, num_blocks=16,
                      max_batch=1, max_queue=4, guard=guard)
    c = _clients(2, np.random.default_rng(3), new_lo=3, new_hi=5)
    for cid, prompt, mn in c:
        eng.submit(prompt, mn, request_id=cid)
    eng.step()  # c0 admitted, guard quiet, c1 still queued
    assert not eng.draining
    guard.requested = True
    eng.step()
    assert eng.draining and eng.drain_reason == "preempt"
    assert eng.outcomes["c1"] == "shed:drained"
    got = eng.run()
    assert sorted(got) == ["c0"] and eng.outcomes["c0"] == "ok"
