"""Static analysis v2 (`ddl_tpu lint`): the whole-program half.

Covers the package-wide call graph (callgraph.py: import/re-export
resolution + reverse-dependency closure), cross-module traced-set
inference (a host sync hidden behind a helper in ANOTHER module is
flagged, fixture-proven with a two-file package), the
collective-symmetry and recompile-hazard rule families, the
dead-event-kind rule, `lint --fix [--check]` round trips
(fix -> clean lint -> second fix is a byte-level no-op), and
`lint --changed`'s git-scoped closure.
"""

import shutil
import subprocess
from pathlib import Path

import pytest

from ddl_tpu.analysis.astlint import (
    lint_file,
    lint_package,
    load_registry,
)
from ddl_tpu.analysis.callgraph import CallGraph
from ddl_tpu.analysis.fixes import plan_fixes

REPO = Path(__file__).resolve().parents[1]
PACKAGE = REPO / "ddl_tpu"
FIXTURES = Path(__file__).parent / "lint_fixtures"
REGISTRY = load_registry(PACKAGE)


def _rules(findings):
    return [f.rule for f in findings]


def _lint_tmp(tmp_path, rel, source):
    p = tmp_path / rel
    p.parent.mkdir(parents=True, exist_ok=True)
    p.write_text(source)
    return lint_file(p, tmp_path, REGISTRY)


def _copy_pkg(tmp_path, fixture_name, as_name):
    dst = tmp_path / as_name
    shutil.copytree(FIXTURES / fixture_name, dst)
    return dst


# ---------------------------------------------------------------------------
# callgraph: resolution + dependency closure (over the real package)
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def graph():
    return CallGraph(PACKAGE)


def test_callgraph_resolves_from_import(graph):
    steps = graph.modules["ddl_tpu.train.steps"]
    t = graph.resolve_dotted(steps, "forward_stages")
    assert t is not None and t.module == "ddl_tpu.models.densenet"
    assert t.func.name == "forward_stages"


def test_callgraph_resolves_reexport_chain(graph):
    # train/steps.py: `from ddl_tpu.ops import cross_entropy_loss` —
    # ops/__init__ re-exports it from ops/losses.py
    steps = graph.modules["ddl_tpu.train.steps"]
    t = graph.resolve_dotted(steps, "cross_entropy_loss")
    assert t is not None and t.module == "ddl_tpu.ops.losses"


def test_callgraph_resolves_module_attribute(graph):
    # supervisor.py: `from ddl_tpu import coord` then coord.acquire_launch
    sup = graph.modules["ddl_tpu.supervisor"]
    t = graph.resolve_dotted(sup, "coord.acquire_launch")
    assert t is not None and t.module == "ddl_tpu.coord"
    assert t.func.name == "acquire_launch"


def test_callgraph_external_names_unresolved(graph):
    steps = graph.modules["ddl_tpu.train.steps"]
    assert graph.resolve_dotted(steps, "jax.jit") is None
    assert graph.resolve_dotted(steps, "no_such_name_anywhere") is None


def test_reverse_closure_contains_importers(graph):
    closure = graph.reverse_closure({"ddl_tpu.obs.events"})
    assert "ddl_tpu.obs.events" in closure
    # steptrace imports events directly; supervisor via its events
    # helper; report/fold downstream
    assert "ddl_tpu.obs.steptrace" in closure
    assert "ddl_tpu.supervisor" in closure
    # an unrelated leaf module must not ride along
    assert "ddl_tpu.utils.backoff" not in closure


# ---------------------------------------------------------------------------
# cross-module traced-set inference (the two-file fixture package)
# ---------------------------------------------------------------------------


def test_cross_module_host_sync_flagged(tmp_path):
    """A host sync reachable ONLY through a helper in a different
    module is flagged (the acceptance scenario): steps.py's jitted step
    calls helpers.sync_mean through the package re-export."""
    pkg = _copy_pkg(tmp_path, "xmod_pkg", "xmod_pkg")
    fs = lint_package(pkg)
    helpers = [f for f in fs if f.path.endswith("helpers.py")]
    # sync_mean: float() + np.asarray, both only traced cross-module
    assert _rules(helpers) == ["host-sync", "host-sync"]
    assert all("sync_mean" in f.message for f in helpers)
    # provenance names the calling module
    assert any("traced:" in f.message and "steps.py" in f.message
               for f in helpers)
    # the host-side caller in the same file stays clean
    assert not any("host_side_report" in f.message for f in helpers)


def test_cross_module_sink_param_flow(tmp_path):
    """steps.py's inner_loss flows into helpers.takes_a_loss_fn's sink
    parameter -> traced -> its float() is flagged in steps.py."""
    pkg = _copy_pkg(tmp_path, "xmod_pkg", "xmod_pkg")
    fs = lint_package(pkg)
    steps = [f for f in fs if f.path.endswith("steps.py")]
    assert any(
        f.rule == "host-sync" and "inner_loss" in f.message for f in steps
    )


def test_method_edges_traced_cross_module(tmp_path):
    """Class-method resolution (PR-13 follow-on): a jitted step calling
    ``m = Model(); m.loss(x)`` pulls the method — and the
    ``self._sync_scalar`` it reaches — under the trace in ANOTHER
    module, inherited methods resolve through the base chain, while
    out-of-package receivers and host-side instance use stay clean."""
    pkg = _copy_pkg(tmp_path, "method_pkg", "method_pkg")
    fs = lint_package(pkg)
    model = [f for f in fs if f.path.endswith("model.py")]
    assert any(
        f.rule == "host-sync" and "_sync_scalar" in f.message
        for f in model
    ), _rules(fs)
    # inherited: Derived() receiver resolves base_sync through Base
    assert any(
        f.rule == "host-sync" and "base_sync" in f.message for f in model
    )
    # the host-side method is never traced
    assert not any("report" in f.message for f in model)
    # provenance names the traced caller's module
    assert any("traced" in f.message and "steps.py" in f.message
               for f in model)
    # the traced-side module itself is clean (the numpy receiver must
    # not resolve, the host driver stays host-side)
    assert [f for f in fs if f.path.endswith("steps.py")] == []


def test_self_method_edge_single_file(tmp_path):
    """``self.m()`` edges work in the single-file engine too: a method
    reference passed to jit traces the method, and the host sync it
    reaches through ``self`` is flagged."""
    fs = _lint_tmp(tmp_path, "selfm.py", (
        "import numpy as np\n"
        "import jax\n"
        "\n"
        "\n"
        "class Trainer:\n"
        "    def step(self, x):\n"
        "        return self._sync(x)\n"
        "\n"
        "    def _sync(self, x):\n"
        "        return float(np.asarray(x).mean())\n"
        "\n"
        "    def host_report(self, x):\n"
        "        return float(np.asarray(x).mean())\n"
        "\n"
        "\n"
        "def make():\n"
        "    tr = Trainer()\n"
        "    return jax.jit(tr.step)\n"
    ))
    sync = [f for f in fs if f.rule == "host-sync"]
    assert any("_sync" in f.message for f in sync), _rules(fs)
    assert not any("host_report" in f.message for f in sync)


def test_callgraph_resolves_class_methods(tmp_path):
    """The resolution layer directly: imported-class instance methods
    and ``mod.Class.method`` dotted references resolve to the defining
    module; external receivers return None."""
    pkg = _copy_pkg(tmp_path, "method_pkg", "method_pkg")
    g = CallGraph(pkg)
    steps = g.modules["method_pkg.steps"]
    t = g.resolve_class_method(steps, "Model", "loss")
    assert t is not None and t.module == "method_pkg.model"
    assert t.func.name == "loss"
    # inherited through the base chain
    t = g.resolve_class_method(steps, "Derived", "base_sync")
    assert t is not None and t.func.name == "base_sync"
    # dotted Cls.method reference
    t = g.resolve_dotted(
        g.modules["method_pkg.steps"], "Model.loss"
    )
    assert t is not None and t.func.name == "loss"
    # external receiver class
    assert g.resolve_class_method(steps, "np.zeros", "sum") is None


def test_single_file_engine_stays_blind_cross_module():
    """lint_file on helpers.py alone must NOT flag sync_mean — nothing
    in that file traces it.  (This is the regression the whole-program
    pass exists to close; if this starts failing the fixture stopped
    isolating the cross-module edge.)"""
    fs = lint_file(
        FIXTURES / "xmod_pkg" / "helpers.py", REPO, REGISTRY
    )
    assert [f for f in fs if f.rule == "host-sync"] == []


# ---------------------------------------------------------------------------
# collective-symmetry
# ---------------------------------------------------------------------------


BARRIER_SRC = (FIXTURES / "bad_conditional_barrier.py").read_text()


def test_conditional_barrier_flagged_in_coord_modules(tmp_path):
    for rel in ("supervisor.py", "coord.py", "train/loop.py"):
        fs = [
            f for f in _lint_tmp(tmp_path, rel, BARRIER_SRC)
            if f.rule == "collective-symmetry"
        ]
        # rank-gated barrier, env-gated arrive, host_id-gated psum
        assert len(fs) == 3, (rel, fs)
        msgs = " | ".join(f.message for f in fs)
        assert "rv.barrier" in msgs and "rv.arrive" in msgs
        assert "lax.psum" in msgs
        assert "DDL_FAST_RESTART" in msgs
    # outside the coordination/step modules the rule does not apply
    assert [
        f for f in _lint_tmp(tmp_path, "bench/lm.py", BARRIER_SRC)
        if f.rule == "collective-symmetry"
    ] == []


def test_symmetric_and_nested_def_paths_not_flagged(tmp_path):
    fs = [
        f for f in _lint_tmp(tmp_path, "coord.py", BARRIER_SRC)
        if f.rule == "collective-symmetry"
    ]
    lines = BARRIER_SRC.splitlines()
    for f in fs:
        flagged = lines[f.line - 1]
        assert "fine" not in flagged, flagged


EARLY_RETURN_SRC = (FIXTURES / "bad_early_return_barrier.py").read_text()


def test_early_return_asymmetry_flagged(tmp_path):
    """`if host: return` before a barrier/collective is the same split
    brain as a barrier inside the branch — the PR-13 follow-on the
    condition-stack walk could not see."""
    fs = [
        f for f in _lint_tmp(tmp_path, "coord.py", EARLY_RETURN_SRC)
        if f.rule == "collective-symmetry"
    ]
    # module-level DDL_*-gated raise, host-gated early return, DDL_*
    # early raise, else-branch return, and the continue-gated barrier
    # inside a for-loop body
    assert len(fs) == 5, fs
    msgs = " | ".join(f.message for f in fs)
    assert "rv.barrier" in msgs and "lax.psum" in msgs and "rv.arrive" in msgs
    assert "early" in msgs
    lines = EARLY_RETURN_SRC.splitlines()
    for f in fs:
        assert "collective-symmetry:" in lines[f.line - 1], lines[f.line - 1]


def test_early_return_known_good_not_flagged(tmp_path):
    """The known-good half: barrier before the split, non-host-gated
    early returns, symmetric both-branches-return, and nested-def
    bodies must all stay clean."""
    fs = [
        f for f in _lint_tmp(tmp_path, "supervisor.py", EARLY_RETURN_SRC)
        if f.rule == "collective-symmetry"
    ]
    lines = EARLY_RETURN_SRC.splitlines()
    for f in fs:
        assert "fine" not in lines[f.line - 1], lines[f.line - 1]
    # outside the coordination/step modules the rule does not apply
    assert [
        f for f in _lint_tmp(tmp_path, "bench/lm.py", EARLY_RETURN_SRC)
        if f.rule == "collective-symmetry"
    ] == []


def test_conditional_barrier_suppression(tmp_path):
    ok = BARRIER_SRC.replace(
        'rv.barrier(f"e{epoch}-join")  # collective-symmetry: rv.host branch',
        'rv.barrier(f"e{epoch}-join")  # ddl-lint: disable=collective-symmetry',
    ).replace(
        'rv.arrive("join")  # collective-symmetry: DDL_* env branch',
        'rv.arrive("join")  # ddl-lint: disable=collective-symmetry',
    ).replace(
        'x = lax.psum(x, "data")  # collective-symmetry: host_id loop',
        'x = lax.psum(x, "data")  # ddl-lint: disable=collective-symmetry',
    )
    assert [
        f for f in _lint_tmp(tmp_path, "supervisor.py", ok)
        if f.rule == "collective-symmetry"
    ] == []


# ---------------------------------------------------------------------------
# recompile-hazard family
# ---------------------------------------------------------------------------


def test_shape_branch_fixture(tmp_path):
    fs = _lint_tmp(
        tmp_path, "m.py", (FIXTURES / "bad_shape_branch.py").read_text()
    )
    shape = [f for f in fs if f.rule == "recompile-shape-branch"]
    # the If on .shape and the IfExp on .dtype; the lone-raise guard and
    # the host-side branch are exempt
    assert len(shape) == 2, shape
    msgs = " | ".join(f.message for f in shape)
    assert ".shape" in msgs and ".dtype" in msgs
    lines = (FIXTURES / "bad_shape_branch.py").read_text().splitlines()
    for f in shape:
        assert "NOT flagged" not in lines[f.line - 1]


def test_mutable_global_fixture(tmp_path):
    fs = _lint_tmp(
        tmp_path, "m.py", (FIXTURES / "bad_mutable_global.py").read_text()
    )
    mg = [f for f in fs if f.rule == "recompile-mutable-global"]
    assert len(mg) == 2, mg
    msgs = " | ".join(f.message for f in mg)
    assert "_CACHE" in msgs and "_SCALES" in msgs
    assert "FROZEN" not in msgs


def test_static_args_fixture(tmp_path):
    fs = _lint_tmp(
        tmp_path, "m.py", (FIXTURES / "bad_static_args.py").read_text()
    )
    unhashable = [f for f in fs if f.rule == "recompile-unhashable-static"]
    fresh = [f for f in fs if f.rule == "recompile-fresh-static"]
    assert len(unhashable) == 2, unhashable  # dict kwarg + list positional
    assert len(fresh) == 2, fresh  # assigned wrapper + decorator form
    src_lines = (FIXTURES / "bad_static_args.py").read_text().splitlines()
    for f in unhashable + fresh:
        assert "fine" not in src_lines[f.line - 1]


def test_recompile_rules_only_inside_traced(tmp_path):
    src = """
def host(x):
    if x.shape[0] > 4:
        return x * 2
    return x
"""
    assert _lint_tmp(tmp_path, "m.py", src) == []


# ---------------------------------------------------------------------------
# dead event kinds
# ---------------------------------------------------------------------------


def test_dead_event_kind_flagged(tmp_path):
    pkg = _copy_pkg(tmp_path, "deadpkg", "deadpkg")
    fs = lint_package(pkg)
    dead = [f for f in fs if f.rule == "obs-event-dead"]
    assert len(dead) == 1, fs
    assert "'ghost'" in dead[0].message
    assert dead[0].path.endswith("obs/events.py")
    # anchored at the registry entry's line
    src_lines = (pkg / "obs" / "events.py").read_text().splitlines()
    assert '"ghost"' in src_lines[dead[0].line - 1]
    # 'external' is unemitted too, but its suppression holds
    assert not any("'external'" in f.message for f in dead)


def test_shipped_event_kinds_all_alive():
    fs = [f for f in lint_package(PACKAGE) if f.rule == "obs-event-dead"]
    assert fs == [], "\n".join(f.format() for f in fs)


# ---------------------------------------------------------------------------
# lint --fix / --check round trips
# ---------------------------------------------------------------------------


def _fix_pkg(tmp_path):
    return _copy_pkg(tmp_path, "fixpkg", "ddl_tpu")


def _pkg_bytes(pkg):
    return {p.relative_to(pkg): p.read_bytes() for p in pkg.rglob("*.py")}


def test_fix_check_diffs_and_writes_nothing(tmp_path, capsys):
    from ddl_tpu.analysis.cli import main

    pkg = _fix_pkg(tmp_path)
    before = _pkg_bytes(pkg)
    rc = main(["--package-root", str(pkg), "--fix", "--check"])
    out = capsys.readouterr().out
    assert rc == 1
    assert _pkg_bytes(pkg) == before, "--check must write nothing"
    assert "--- a/ddl_tpu/runtime.py" in out
    assert "+from jax import shard_map" in out
    assert "+SPEC = TOKEN_SPEC" in out


def test_fix_round_trip_clean_then_byte_noop(tmp_path, capsys):
    from ddl_tpu.analysis.cli import main

    pkg = _fix_pkg(tmp_path)
    rc = main(["--package-root", str(pkg), "--fix"])
    out = capsys.readouterr().out
    assert rc == 0 and "fixed" in out

    runtime = (pkg / "runtime.py").read_text()
    assert "from jax import shard_map" in runtime
    assert "check_vma=False" in runtime and "check_rep=" not in runtime
    assert "except Exception:" in runtime
    steps = (pkg / "train" / "steps.py").read_text()
    assert "SPEC = TOKEN_SPEC" in steps and "OTHER = BATCH_SPEC" in steps
    assert "from ddl_tpu.parallel.rules import BATCH_SPEC, TOKEN_SPEC" in steps
    events = (pkg / "obs" / "events.py").read_text()
    assert '"new_kind"' in events

    # fixed tree lints clean
    rc = main(["--package-root", str(pkg)])
    capsys.readouterr()
    assert rc == 0

    # second --fix: byte-level no-op, and --check agrees
    before = _pkg_bytes(pkg)
    rc = main(["--package-root", str(pkg), "--fix"])
    capsys.readouterr()
    assert rc == 0
    assert _pkg_bytes(pkg) == before
    rc = main(["--package-root", str(pkg), "--fix", "--check"])
    out = capsys.readouterr().out
    assert rc == 0 and "nothing to fix" in out


_DONATION_SRC = '''\
import jax

train_step = jax.jit(
    _train_step,
    static_argnames=("cfg",),
)
eval_step = jax.jit(_eval_step)
other_train = jax.jit(_other_train_step, static_argnames=("cfg",),)
'''


def test_fix_donation_missing_inserts_donate_argnums(tmp_path):
    """`lint --fix` on donation-missing: donate_argnums=(0,) lands in
    the jit(train...) calls — multi-line and trailing-comma shapes —
    eval steps are untouched, and a second fix is a byte no-op."""
    p = tmp_path / "train" / "steps.py"
    p.parent.mkdir(parents=True)
    p.write_text(_DONATION_SRC)
    findings = lint_file(p, tmp_path, REGISTRY)
    assert _rules(findings).count("donation-missing") == 2

    plan = plan_fixes(findings, tmp_path, tmp_path)
    assert [f.rule for f in plan.fixed].count("donation-missing") == 2
    plan.apply()
    fixed = p.read_text()
    compile(fixed, str(p), "exec")  # still valid python
    assert fixed.count("donate_argnums=(0,)") == 2
    assert "jax.jit(_eval_step)" in fixed  # eval step untouched

    # fixed file lints clean and a second pass changes nothing
    findings2 = lint_file(p, tmp_path, REGISTRY)
    assert "donation-missing" not in _rules(findings2)
    plan2 = plan_fixes(findings2, tmp_path, tmp_path)
    plan2.apply()
    assert p.read_text() == fixed


def test_fix_donation_missing_respects_existing_donation(tmp_path):
    """A jit(train...) already carrying donate_argnums (positional
    tuple or keyword) is not a finding and survives --fix untouched —
    the autofix must never double-insert or rewrite a working
    donation."""
    p = tmp_path / "train" / "steps.py"
    p.parent.mkdir(parents=True)
    p.write_text(
        "import jax\n\n"
        "train_step = jax.jit(_train_step, donate_argnums=(0,))\n"
        "other_train = jax.jit(\n"
        "    _other_train_step,\n"
        "    static_argnames=('cfg',),\n"
        "    donate_argnums=(0, 1),\n"
        ")\n"
    )
    before = p.read_text()
    findings = lint_file(p, tmp_path, REGISTRY)
    assert "donation-missing" not in _rules(findings)
    plan = plan_fixes(findings, tmp_path, tmp_path)
    plan.apply()
    assert p.read_text() == before


def test_fix_is_deterministic(tmp_path, capsys):
    from ddl_tpu.analysis.cli import main

    a = _copy_pkg(tmp_path / "a", "fixpkg", "ddl_tpu")
    b = _copy_pkg(tmp_path / "b", "fixpkg", "ddl_tpu")
    main(["--package-root", str(a), "--fix"])
    main(["--package-root", str(b), "--fix"])
    capsys.readouterr()
    assert _pkg_bytes(a) == _pkg_bytes(b)


def test_fix_preserves_import_aliases(tmp_path):
    """Extending an existing rules import must keep `as` aliases — the
    module's alias uses would otherwise NameError at import."""
    pkg = _fix_pkg(tmp_path)
    steps = pkg / "train" / "steps.py"
    steps.write_text(
        '"""doc"""\n'
        "from jax.sharding import PartitionSpec as P\n\n"
        "from ddl_tpu.parallel.rules import BATCH_SPEC as BS\n\n"
        "OTHER = BS\n"
        'SPEC = P(("data", "expert"), "seq")\n'
    )
    plan = plan_fixes(lint_package(pkg), pkg.parent, pkg)
    plan.apply()
    fixed = steps.read_text()
    assert (
        "from ddl_tpu.parallel.rules import BATCH_SPEC as BS, TOKEN_SPEC"
        in fixed
    )
    assert "OTHER = BS" in fixed and "SPEC = TOKEN_SPEC" in fixed


def test_fix_registry_insert_survives_trailing_comment(tmp_path):
    """A trailing comment on the last EVENT_KINDS entry must not swallow
    the inserted comma (implicit string concatenation would silently
    merge two kinds)."""
    import ast as ast_mod

    pkg = _fix_pkg(tmp_path)
    events = pkg / "obs" / "events.py"
    events.write_text(
        'EVENT_KINDS = (\n    "span",  # the envelope kind\n'
        '    "last"  # no trailing comma\n)\n'
    )
    plan = plan_fixes(lint_package(pkg), pkg.parent, pkg)
    plan.apply()
    src = events.read_text()
    tree = ast_mod.parse(src)
    kinds = [
        e.value for e in ast_mod.walk(tree)
        if isinstance(e, ast_mod.Constant) and isinstance(e.value, str)
    ]
    assert set(kinds) >= {"span", "last", "new_kind"}, src


def test_changed_update_baseline_rejected():
    from ddl_tpu.analysis.cli import main

    with pytest.raises(SystemExit) as e:
        main(["--changed", "--update-baseline"])
    assert e.value.code == 2


def test_unmatched_pspec_literal_is_unfixable(tmp_path):
    pkg = _fix_pkg(tmp_path)
    steps = pkg / "train" / "steps.py"
    steps.write_text(
        steps.read_text() + 'NO_CONSTANT = P("model", "seq")\n'
    )
    findings = lint_package(pkg)
    plan = plan_fixes(findings, pkg.parent, pkg)
    assert any(
        f.rule == "pspec-hand-rolled" and "model" in f.message
        for f in plan.unfixable
    )
    # the matchable literals are still planned
    assert any(f.rule == "pspec-hand-rolled" for f in plan.fixed)


# ---------------------------------------------------------------------------
# lint --changed (git-scoped closure)
# ---------------------------------------------------------------------------


def _git(repo, *args):
    subprocess.run(
        ["git", "-c", "user.email=t@t", "-c", "user.name=t", *args],
        cwd=repo, check=True, capture_output=True,
    )


@pytest.fixture()
def changed_repo(tmp_path):
    repo = tmp_path / "repo"
    pkg = repo / "ddl_tpu"
    pkg.mkdir(parents=True)
    (pkg / "base.py").write_text("def helper(x):\n    return x\n")
    (pkg / "mid.py").write_text(
        "from ddl_tpu.base import helper\n\n"
        "def use(x):\n    return helper(x)\n"
    )
    (pkg / "leaf.py").write_text("def lonely(x):\n    return x\n")
    _git(repo, "init", "-q")
    _git(repo, "add", "-A")
    _git(repo, "commit", "-qm", "seed")
    return repo, pkg


def test_changed_scopes_to_reverse_closure(changed_repo, capsys):
    from ddl_tpu.analysis.cli import main

    repo, pkg = changed_repo
    rc = main(["--package-root", str(pkg), "--changed"])
    out = capsys.readouterr().out
    assert rc == 0 and "no changed package modules" in out

    # edit base.py: mid.py (importer) joins the scope, leaf.py does not
    (pkg / "base.py").write_text(
        "def helper(x):\n    return x + 1\n"
    )
    rc = main(["--package-root", str(pkg), "--changed"])
    out = capsys.readouterr().out
    assert rc == 0
    assert "1 changed module(s) + 1 reverse dependent(s)" in out


def test_changed_reports_cross_module_finding(changed_repo, capsys):
    """A traced host sync introduced in a HELPER is reported when only
    the helper changed — the reverse-dep closure pulls the traced
    caller in, and inference over the full graph attributes it."""
    from ddl_tpu.analysis.cli import main

    repo, pkg = changed_repo
    (pkg / "mid.py").write_text(
        "import jax\n\nfrom ddl_tpu.base import helper\n\n"
        "def make(tx):\n"
        "    def step(x):\n"
        "        return helper(x)\n"
        "    return jax.jit(step)\n"
    )
    _git(repo, "add", "-A")
    _git(repo, "commit", "-qm", "traced caller")
    (pkg / "base.py").write_text(
        "def helper(x):\n    return float(x.sum())\n"
    )
    rc = main(["--package-root", str(pkg), "--changed"])
    out = capsys.readouterr().out
    assert rc == 1
    assert "ddl_tpu/base.py:2: [host-sync]" in out


# ---------------------------------------------------------------------------
# CLI acceptance: seeded violations fail with file:line findings
# ---------------------------------------------------------------------------


def test_cli_seeded_barrier_and_shape_branch_fail(tmp_path, capsys):
    from ddl_tpu.analysis.cli import main

    pkg = tmp_path / "ddl_tpu"
    pkg.mkdir()
    shutil.copy(
        FIXTURES / "bad_conditional_barrier.py", pkg / "supervisor.py"
    )
    shutil.copy(FIXTURES / "bad_shape_branch.py", pkg / "steps_probe.py")
    rc = main(["--package-root", str(pkg)])
    out = capsys.readouterr().out
    assert rc == 1
    assert "ddl_tpu/supervisor.py:14: [collective-symmetry]" in out
    assert "[recompile-shape-branch]" in out
    assert "ddl_tpu/steps_probe.py:12:" in out


# ---------------------------------------------------------------------------
# shipped package stays clean under the new rules
# ---------------------------------------------------------------------------


def test_shipped_package_clean_under_v2_rules():
    new_rules = {
        "collective-symmetry",
        "recompile-shape-branch",
        "recompile-mutable-global",
        "recompile-unhashable-static",
        "recompile-fresh-static",
        "obs-event-dead",
    }
    fs = [f for f in lint_package(PACKAGE) if f.rule in new_rules]
    assert fs == [], "\n".join(f.format() for f in fs)
