"""Metric suite vs sklearn (the reference's metric source, single.py:11)."""

import numpy as np
import pytest

from ddl_tpu.utils import metrics as M

sklearn_metrics = pytest.importorskip("sklearn.metrics")


@pytest.fixture(params=[0, 1, 2, 3])
def labels_pair(request):
    rng = np.random.default_rng(request.param)
    n = 500
    if request.param == 3:
        # degenerate: a class never predicted, a class never true
        y_true = rng.integers(0, 4, n)
        y_pred = rng.integers(1, 5, n)
    else:
        y_true = rng.integers(0, 5, n)
        # correlated predictions so kappa is nontrivial
        y_pred = np.where(rng.random(n) < 0.6, y_true, rng.integers(0, 5, n))
    return y_true, y_pred


def test_accuracy(labels_pair):
    y, p = labels_pair
    assert M.accuracy_score(y, p) == pytest.approx(sklearn_metrics.accuracy_score(y, p))


@pytest.mark.parametrize("average", ["macro", "weighted"])
def test_prf(labels_pair, average):
    y, p = labels_pair
    assert M.f1_score(y, p, average) == pytest.approx(
        sklearn_metrics.f1_score(y, p, average=average, zero_division=0)
    )
    assert M.precision_score(y, p, average) == pytest.approx(
        sklearn_metrics.precision_score(y, p, average=average, zero_division=0)
    )
    assert M.recall_score(y, p, average) == pytest.approx(
        sklearn_metrics.recall_score(y, p, average=average, zero_division=0)
    )


def test_qwk(labels_pair):
    y, p = labels_pair
    assert M.quadratic_weighted_kappa(y, p) == pytest.approx(
        sklearn_metrics.cohen_kappa_score(y, p, weights="quadratic")
    )


def test_cross_entropy_matches_torch():
    torch = pytest.importorskip("torch")
    rng = np.random.default_rng(0)
    logits = rng.normal(size=(64, 5)).astype(np.float32)
    targets = rng.integers(0, 5, 64)
    expected = torch.nn.functional.cross_entropy(
        torch.tensor(logits), torch.tensor(targets)
    ).item()
    assert M.cross_entropy(logits, targets) == pytest.approx(expected, rel=1e-5)


def test_classification_metrics_keys():
    y = np.array([0, 1, 2, 3, 4, 0])
    p = np.array([0, 1, 2, 3, 4, 1])
    out = M.classification_metrics(y, p)
    # exactly the metric names the reference logs (single.py:244-251)
    assert set(out) == {
        "val_accuracy",
        "macro_f1",
        "weighted_f1",
        "macro_precision",
        "weighted_precision",
        "macro_recall",
        "weighted_recall",
        "qwk",
    }
