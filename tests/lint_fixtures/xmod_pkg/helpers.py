"""Known-bad fixture: helpers that are only traced CROSS-MODULE.

Nothing in this file is traced on its own — no transform call, no
decorator.  ``sync_mean`` becomes traced because ``steps.py``'s jitted
step calls it (through the package re-export), and ``takes_a_loss_fn``
is a sink whose callers' arguments land under ``value_and_grad``.  The
single-file engine sees a clean module; the whole-program pass must
flag both host syncs.  Parsed by tests/test_lint_v2.py — never
imported."""

import numpy as np

import jax


def sync_mean(x):
    # host-sync, but ONLY when reached from steps.py's traced step
    return float(np.asarray(x).mean())


def takes_a_loss_fn(f):
    # sink parameter: anything passed as `f` from ANY module lands
    # under a trace here
    return jax.value_and_grad(f)


def host_side_report(xs):
    # never traced: a host-side caller may sync freely
    return float(np.mean([np.asarray(x) for x in xs]))
