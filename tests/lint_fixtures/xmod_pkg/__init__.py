"""Known-bad fixture PACKAGE: cross-module traced-set inference.
Re-exports ``sync_mean`` so ``steps.py`` can reach it through the
package ``__init__`` — the re-export chase the callgraph must follow.
Parsed by tests/test_lint_v2.py — never imported."""

from .helpers import sync_mean, takes_a_loss_fn

__all__ = ["sync_mean", "takes_a_loss_fn"]
