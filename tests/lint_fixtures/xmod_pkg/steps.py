"""Known-bad fixture: the traced side of the cross-module pair.

``train_step`` is jitted here; the helpers it pulls under the trace
live in ``helpers.py`` (one reached through the package re-export, one
through a module attribute, one by flowing into a foreign sink
parameter).  Parsed by tests/test_lint_v2.py — never imported."""

import jax

from xmod_pkg import sync_mean
from xmod_pkg import helpers


def make_step(tx):
    def train_step(state, x):
        loss = (x * x).sum()
        # cross-module call from traced code, via the __init__ re-export:
        # helpers.sync_mean's float(np.asarray(...)) must be flagged THERE
        m = sync_mean(loss)
        return state, loss + m

    return jax.jit(train_step, donate_argnums=(0,))


def make_other():
    def inner_loss(p, x):
        return float(x.mean())  # traced via helpers.takes_a_loss_fn's sink

    return helpers.takes_a_loss_fn(inner_loss)
