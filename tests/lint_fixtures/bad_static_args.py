"""Known-bad fixture: recompile-unhashable-static /
recompile-fresh-static — hazardous arguments at jit static boundaries.
The module-constant and value-hashed call sites must NOT be flagged.
Parsed by tests/test_lint_v2.py — never imported."""

from functools import partial

import jax

CFG = ("adam", 0.1)


def apply_model(x, cfg):
    return x * len(cfg)


wrapped = jax.jit(apply_model, static_argnames=("cfg",))
by_pos = jax.jit(apply_model, static_argnums=(1,))


@partial(jax.jit, static_argnames=("mode",))
def decorated(x, mode):
    return x if mode == "train" else x * 0


def drive(x, make_cfg):
    wrapped(x, cfg={"opt": "adam"})  # recompile-unhashable-static (dict)
    wrapped(x, cfg=make_cfg())  # recompile-fresh-static (ctor per call)
    by_pos(x, [1, 2])  # recompile-unhashable-static (list, positional)
    decorated(x, mode=make_cfg())  # recompile-fresh-static (decorator form)
    wrapped(x, cfg=CFG)  # module constant: fine
    wrapped(x, cfg=tuple(x))  # value-hashed builtin: fine
    return x
