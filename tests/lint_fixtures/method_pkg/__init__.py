"""Known-bad fixture PACKAGE: class-method edges in the traced-set
inference (``self.m()`` within a class, ``obj.m()`` through a
conservative ``obj = C(...)`` binding, locally and across modules).
Parsed by tests/test_lint_v2.py — never imported."""
