"""Known-bad fixture: methods that are only traced through method
edges.  Nothing here is traced on its own; ``steps.py``'s jitted step
calls ``Model.loss`` on an instance, and ``loss`` reaches
``_sync_scalar`` through ``self``.  Parsed by tests — never imported."""

import numpy as np


class Model:
    def loss(self, x):
        # traced via steps.py's `m = Model(); m.loss(x)` inside a jit
        y = (x * x).sum()
        return y + self._sync_scalar(y)

    def _sync_scalar(self, y):
        # host-sync, reached ONLY through the self.m() edge
        return float(np.asarray(y).mean())

    def report(self, xs):
        # never traced: a host-side method may sync freely
        return float(np.mean(xs))


class Base:
    def base_sync(self, y):
        # host-sync, reached through an inherited-method edge
        return float(np.asarray(y).sum())


class Derived(Base):
    pass
