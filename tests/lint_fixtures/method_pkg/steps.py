"""Known-bad fixture: the traced side of the method-edge pair.  The
jitted steps pull methods under the trace: a same-module ``self.m()``
chain, a cross-module ``obj.m()`` on a ``Model()`` instance, and an
inherited method on a ``Derived()`` instance.  Parsed by tests —
never imported."""

import numpy as np

import jax

from method_pkg.model import Derived, Model


def make_step():
    def train_step(state, x):
        m = Model()
        # cross-module obj.m() from traced code: Model.loss (and the
        # self._sync_scalar it calls) must be flagged in model.py
        return state, m.loss(x)

    return jax.jit(train_step)


def make_inherited_step():
    def inherited_step(x):
        d = Derived()
        # inherited method: resolves through Derived -> Base
        return d.base_sync(x)

    return jax.jit(inherited_step)


def make_external_step():
    def external_step(x):
        buf = np.zeros(4)
        # out-of-package receiver: the `buf = np.zeros(...)` binding
        # must NOT resolve through the graph (numpy is external), so
        # this stays clean
        return x + buf.sum()

    return jax.jit(external_step)


def host_driver(xs):
    # host-side instance use: Model.report stays untraced
    m = Model()
    return m.report(xs)
