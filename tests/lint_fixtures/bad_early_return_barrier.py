"""Known-bad/known-good fixture: early-return asymmetry — a
host-dependent branch that LEAVES the function makes every later
statement in the suite reachable only by the hosts that stayed, so a
barrier/collective after it is the same split-brain hang as one inside
the branch.  Linted by tests with a coord-module rel path; parsed by
tests/test_lint_v2.py — never imported."""

import os

from jax import lax

# module-level early exit: the suite-aware walk covers import-time code
# too — everything below the raise runs only on hosts that skipped it
if os.environ.get("DDL_SKIP_MODULE_INIT"):
    raise SystemExit(0)

_INIT = lax.psum(1, "data")  # collective-symmetry: module-level DDL_* gate


def early_return_then_barrier(rv, host_id):
    if host_id != 0:
        return None
    rv.barrier("propose")  # collective-symmetry: only host 0 arrives
    return rv


def env_gated_raise_then_psum(x):
    if os.environ.get("DDL_SKIP_REDUCE"):
        raise RuntimeError("skipped")
    return lax.psum(x, "data")  # collective-symmetry: DDL_* early raise


def early_return_else_branch(rv, host):
    if host == 0:
        pass
    else:
        return None
    rv.arrive("leader-only")  # collective-symmetry: non-leaders left


def continue_gated_barrier_in_loop(rv, host_id, steps):
    for step in range(steps):
        if host_id != 0:
            continue
        rv.barrier(f"tick-{step}")  # collective-symmetry: host 0 only
    return steps


def loop_barrier_after_symmetric_skip(rv, ready, steps):
    for step in range(steps):
        if not ready:
            continue
        rv.barrier(f"tick-{step}")  # fine: the skip is not host-gated
    return steps


def barrier_before_early_return(rv, host_id):
    rv.barrier("start")  # fine: every host arrives before the split
    if host_id != 0:
        return None
    return rv


def early_return_not_host_dependent(rv, ready):
    if not ready:
        return None
    rv.barrier("start")  # fine: the early return is not host-gated


def both_branches_return(rv, host_id):
    # symmetric: EVERY host leaves here, nothing below is reachable
    if host_id == 0:
        return "leader"
    else:
        return "follower"
    rv.barrier("dead")  # fine: dead code, no host reaches it


def early_return_in_nested_def(rv, host_id):
    # the nested body resets the suite taint — defining a function
    # under a host branch is not calling one
    def helper():
        if host_id != 0:
            return None
        return rv

    helper()
    rv.barrier("join")  # fine: every host calls this
    return rv
