"""Fixture module carrying every remaining autofixable class: legacy
shard_map import, check_rep kwarg (on a continuation line), bare
except, and an emitted-but-unregistered event kind.  Copied to a tmp
``ddl_tpu`` package by tests/test_lint_v2.py — never imported."""

from jax.experimental.shard_map import shard_map


def wrap(writer, f, mesh):
    writer.emit("span")
    writer.emit("new_kind", x=1)
    try:
        return shard_map(f, mesh=mesh, in_specs=None, out_specs=None,
                         check_rep=False)
    except:
        return None
