"""Fixture step-factory module with hand-rolled PartitionSpec literals
whose values match the rule-table constants — ``--fix`` must rewrite
both to the constant names and add the import.  Copied to a tmp
``ddl_tpu`` package by tests/test_lint_v2.py — never imported."""

from jax.sharding import PartitionSpec as P

SPEC = P(("data", "expert"), "seq")
OTHER = P("data")
