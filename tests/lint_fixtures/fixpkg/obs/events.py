"""Fixture mini-registry for the autofix round-trip tests: the
``--fix`` run must append ``runtime.py``'s unregistered kind here.
Copied to a tmp ``ddl_tpu`` package by tests/test_lint_v2.py — never
imported."""

EVENT_KINDS = (
    "span",
)

ANOMALY_TYPES = (
    "loss_spike",
)
