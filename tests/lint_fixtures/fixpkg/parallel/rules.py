"""Fixture mini rule table: the constants the pspec autofix rewrites
hand-rolled literals to (values mirror the real parallel/rules.py).
Copied to a tmp ``ddl_tpu`` package by tests/test_lint_v2.py — never
imported."""

from jax.sharding import PartitionSpec as P

BATCH_SPEC = P("data")
TOKEN_SPEC = P(("data", "expert"), "seq")
