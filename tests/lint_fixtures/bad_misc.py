"""Known-bad fixture: pspec / compat / obs-event / bare-except
violations.  Parsed by tests/test_analysis.py — never imported."""

from jax.experimental.shard_map import shard_map  # compat-bypass
from jax.sharding import Mesh, PartitionSpec as P

BAD_SPEC = P("data", "batch_x")  # pspec-unknown-axis ('batch_x')
OK_SPEC = P(("data", "expert"), "seq")

# a module-declared mesh axis extends the allowed vocabulary
RING_MESH_AXES = ("ring",)


def build_ring(devices):
    return Mesh(devices, ("ring",))


RING_SPEC = P("ring")  # fine: declared by the Mesh literal above


def legacy_shard(f, mesh):
    return shard_map(
        f, mesh=mesh, in_specs=P("data"), out_specs=P("data"),
        check_rep=False,  # compat-bypass: legacy kwarg
    )


def emit_things(writer, obs):
    writer.emit("period", step=0)  # registered: fine
    writer.emit("detonation", step=0)  # obs-event-unregistered
    obs.anomaly.record(3, "loss_spike", value=1.0)  # registered: fine
    obs.anomaly.record(3, "gremlins", value=1.0)  # anomaly-type-unregistered


def swallow_everything(fn):
    try:
        return fn()
    except:  # noqa: E722  bare-except (flagged package-wide)
        return None
