"""Known-bad fixture: the causal-trace event kinds.  The REGISTERED
kinds (``trace_span``/``trace_mark``, obs/events.py) must pass the
obs-event rule; an unregistered trace-ish kind must still fail — the
regression this fixture pins is a future trace emitter inventing a kind
without registering it, which would silently drop that span class from
every ``obs trace`` output.  Parsed by tests/test_analysis.py — never
imported."""


def emit_trace(writer):
    writer.emit(
        "trace_span", trace="r1", span="r1/req", parent=None,
        name="request", t0=0.0, t1=1.0,
    )  # registered: fine
    writer.emit(
        "trace_mark", trace="r1", span="r1/shed", name="shed",
    )  # registered: fine
    writer.emit(
        "trace_hop", trace="r1", span="r1/hop", name="hop",
    )  # obs-event-unregistered
