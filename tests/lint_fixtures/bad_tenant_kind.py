"""Known-bad fixture: tenant-tagged serving kinds.  The REGISTERED
kinds (``serve_admit``/``serve_shed``, obs/events.py) pass the
obs-event rule with or without ``tenant``/``priority_class`` tags —
the tags are optional FIELDS, not new kinds; an unregistered
tenant-tagged kind must still fail.  The regression this fixture pins
is a future multi-tenant emitter assuming the tenant tag exempts it
from the registry, which would silently drop that tenant's events from
every per-tenant digest, SLO budget, and goodput account.  Parsed by
tests/test_analysis.py — never imported."""


def emit_tenant(writer):
    writer.emit(
        "serve_admit", request_id="r1", queue_depth=0,
        tenant="acme", priority_class="interactive",
    )  # registered: the tenant tag rides an existing kind — fine
    writer.emit(
        "serve_shed", request_id="r2", reason="queue_full",
        tenant="acme", priority_class="interactive",
    )  # registered: fine
    writer.emit(
        "tenant_quota", tenant="acme", priority_class="interactive",
        remaining=0,
    )  # obs-event-unregistered
