"""Known-bad fixture: collective-symmetry violations — barriers /
collectives reachable only under host-dependent conditions.  Linted by
tests with a coord-module rel path (supervisor.py / coord.py /
train/loop.py); the rule does not apply elsewhere.  Parsed by
tests/test_lint_v2.py — never imported."""

import os

from jax import lax


def rank_gated_barrier(rv, epoch):
    if rv.host == 0:
        rv.barrier(f"e{epoch}-join")  # collective-symmetry: rv.host branch
    return epoch


def env_gated_arrive(rv):
    if os.environ.get("DDL_FAST_RESTART"):
        rv.arrive("join")  # collective-symmetry: DDL_* env branch
    rv.barrier("start")  # unconditional: fine


def conditional_psum(x, host_id):
    while host_id != 0:
        x = lax.psum(x, "data")  # collective-symmetry: host_id loop
    return x


def symmetric_protocol(rv, compute_fn):
    # every host runs the same sequence: none of these may be flagged
    rv.barrier("start")
    value = rv.agree("resume", compute_fn)
    rv.arrive("done")
    return value


def defines_under_condition(rv, host_id):
    # a function DEFINED under a host branch is not a call made under
    # it — the nested body resets the condition stack
    if host_id == 0:
        def proposer():
            return rv.barrier("propose")  # fine: definition, not a call

        return proposer
    return None
