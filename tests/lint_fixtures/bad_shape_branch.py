"""Known-bad fixture: recompile-shape-branch — Python branching on
traced ``.shape``/``.dtype``.  The lone-raise guard clause and the
host-side branch must NOT be flagged.  Parsed by tests/test_lint_v2.py
— never imported."""

import jax
import jax.numpy as jnp


def make_step():
    def step(x):
        if x.shape[0] > 4:  # recompile-shape-branch (If on .shape)
            y = x * 2
        else:
            y = x + 1
        z = x.sum() if x.dtype == jnp.float32 else x.mean()  # recompile-shape-branch (IfExp on .dtype)
        if x.shape[0] % 2:  # guard clause: lone raise -> NOT flagged
            raise ValueError("odd batch")
        return y + z

    return jax.jit(step)


def host_side_bucketing(x):
    # not traced: factory-level shape dispatch is the recommended fix
    if x.shape[0] > 4:
        return "big"
    return "small"
