"""Known-bad fixture: every host-sync / nondeterminism violation class
inside traced code, plus the sink-parameter interprocedural flow.
Parsed by tests/test_analysis.py — never imported or executed."""

import random
import time

import jax
import numpy as np


def make_step(tx):
    def loss_fn(params, x, y):
        t = time.time()  # nondeterminism: wall clock in trace
        r = random.random()  # nondeterminism: host RNG in trace
        v = float(x.sum())  # host-sync: float() on a tracer
        return v + t + r

    def train_step(state, x, y):
        loss = loss_fn(state.params, x, y)
        loss.item()  # host-sync: .item()
        np.asarray(loss)  # host-sync: np.asarray
        jax.device_get(loss)  # host-sync: device_get
        loss.block_until_ready()  # host-sync: block_until_ready
        for k in {"a", "b"}:  # nondeterminism: set iteration
            loss = loss + 1
        return state, loss

    return jax.jit(train_step, donate_argnums=(0,))


def takes_a_loss_fn(f):
    # sink parameter: anything passed as `f` lands under a trace
    return jax.value_and_grad(f)


def make_other():
    def inner_loss(p, x):
        return float(x.mean())  # host-sync via the sink-param flow

    return takes_a_loss_fn(inner_loss)
