"""Known-good fixture: the same idioms done right — none of these may
produce a finding.  Parsed by tests/test_analysis.py — never imported."""

import time

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

TOKEN_SPEC = P(("data", "expert"), "seq")


def make_step(tx):
    def loss_fn(params, x, y):
        logits = x @ params
        return jnp.mean((logits - y) ** 2)

    def train_step(state, x, y):
        loss, grads = jax.value_and_grad(loss_fn)(state, x, y)
        # dtype cast, not concretization — allowed in traced code
        return state - 0.1 * grads, loss.astype(jnp.float32)

    return jax.jit(train_step, donate_argnums=(0,))


def host_side_epoch_loop(step, state, batches):
    # host code may sync, time, and convert freely
    losses = []
    t0 = time.time()
    for x, y in batches:
        state, loss = step(state, x, y)
        losses.append(loss)
    mean = float(np.mean([np.asarray(l) for l in losses]))
    return state, mean, time.time() - t0


def careful_io(path):
    try:
        return open(path).read()
    except (OSError, ValueError):
        return None
