"""Fixture emitter: keeps ``span`` alive so only ``ghost`` (and the
suppressed ``external``) go unemitted.  Copied to a tmp package by
tests/test_lint_v2.py — never imported."""


def beat(writer):
    writer.emit("span", step=0)
