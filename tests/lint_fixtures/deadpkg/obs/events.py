"""Fixture registry with a DEAD kind: ``ghost`` is registered but
nothing in the package emits it — the dead-event-kind rule must flag
it at this file's EVENT_KINDS line.  ``external`` is also unemitted
but carries a suppression (the justified-keep escape hatch).  Copied
to a tmp package by tests/test_lint_v2.py — never imported."""

EVENT_KINDS = (
    "span",
    "ghost",
    "external",  # ddl-lint: disable=obs-event-dead  (emitted by an external agent)
)
