"""Known-bad fixture: the HBM-ledger event kinds.  The REGISTERED
kinds (``hbm_plan``/``hbm_sample``/``hbm_oom_dump``, obs/events.py)
must pass the obs-event rule; an unregistered memory-ish kind must
still fail — the regression this fixture pins is a future memory
emitter inventing a kind without registering it, which would silently
drop that category from every ``obs hbm`` account (an exhaustive
ledger with an invisible consumer is not exhaustive).  Parsed by
tests/test_analysis.py — never imported."""


def emit_memory(writer):
    writer.emit(
        "hbm_plan", label="train_step", analysis="compiled",
        argument_bytes=1000, output_bytes=1000, temp_bytes=200,
    )  # registered: fine
    writer.emit(
        "hbm_sample", watermark=2000, params_bytes=600, opt_bytes=1200,
    )  # registered: fine
    writer.emit(
        "hbm_oom_dump", error="oom", watermark=4000, buffers=[],
    )  # registered: fine
    writer.emit(
        "hbm_leak_report", leaked=4096,
    )  # obs-event-unregistered
