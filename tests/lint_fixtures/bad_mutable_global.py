"""Known-bad fixture: recompile-mutable-global — traced functions
closing over mutable module globals.  The immutable constant and the
host-side reader must NOT be flagged.  Parsed by tests/test_lint_v2.py
— never imported."""

import jax

_CACHE = {}  # mutated by host code between steps
_SCALES = [1.0, 0.5]
FROZEN = (1.0, 0.5)


def make_step():
    def step(x):
        # both reads bake the trace-time value into the program
        y = x * _SCALES[0]  # recompile-mutable-global (_SCALES)
        return y + len(_CACHE)  # recompile-mutable-global (_CACHE)

    return jax.jit(step)


def make_clean_step():
    def step(x):
        return x * FROZEN[0]  # immutable constant: fine

    return jax.jit(step)


def host_lookup(key):
    return _CACHE.get(key)  # not traced: fine


def shadowed(_SCALES):
    def step(x):
        return x * _SCALES[0]  # parameter shadows the global: fine

    return jax.jit(step)
