"""torchvision state-dict conversion: key mapping, transposes, head swap."""

import jax
import numpy as np
import pytest

from ddl_tpu.models import build_stages, init_stages
from ddl_tpu.models.convert import _torch_key, convert_torch_state_dict


@pytest.fixture(scope="module")
def staged(tiny_model_cfg):
    stages = build_stages(tiny_model_cfg)
    params, batch_stats = init_stages(stages, jax.random.key(0), image_size=16)
    return stages, params, batch_stats


def _fake_torch_sd(params, batch_stats, num_classes_torch=1000):
    """Build a torch-style state dict shaped to match our tree (values
    deterministic per key so conversion can be verified)."""
    sd = {}
    for tree in (*params, *batch_stats):
        for path, leaf in jax.tree_util.tree_flatten_with_path(tree)[0]:
            key = _torch_key(path, is_stats=False)
            arr = np.asarray(leaf)
            if arr.ndim == 4:
                shape = (arr.shape[3], arr.shape[2], arr.shape[0], arr.shape[1])
            elif arr.ndim == 2:
                if "classifier" in key:
                    shape = (num_classes_torch, arr.shape[0])  # ImageNet head
                else:
                    shape = arr.shape[::-1]
            else:
                if "classifier" in key:
                    shape = (num_classes_torch,)
                else:
                    shape = arr.shape
            rng = np.random.default_rng(abs(hash(key)) % 2**32)
            val = rng.normal(size=shape).astype(np.float32)
            if key.endswith("running_var"):
                val = np.abs(val) + 0.5  # variances must be positive
            sd[key] = val
    return sd


def test_key_mapping():
    import jax.tree_util as jtu

    p = (jtu.DictKey("denseblock1"), jtu.DictKey("denselayer2"), jtu.DictKey("conv1"), jtu.DictKey("kernel"))
    assert _torch_key(p, False) == "features.denseblock1.denselayer2.conv1.weight"
    p2 = (jtu.DictKey("norm0"), jtu.DictKey("scale"))
    assert _torch_key(p2, False) == "features.norm0.weight"
    p3 = (jtu.DictKey("classifier"), jtu.DictKey("kernel"))
    assert _torch_key(p3, False) == "classifier.weight"
    p4 = (jtu.DictKey("transition1"), jtu.DictKey("norm"), jtu.DictKey("mean"))
    assert _torch_key(p4, False) == "features.transition1.norm.running_mean"


def test_conversion_overlays_and_transposes(staged):
    stages, params, batch_stats = staged
    sd = _fake_torch_sd(params, batch_stats)
    new_params, new_stats, skipped = convert_torch_state_dict(sd, params, batch_stats)

    # every non-classifier tensor must be overlaid
    assert all("classifier" in k for k in skipped), skipped
    # conv kernel transposed OIHW->HWIO
    k = np.asarray(new_params[0]["conv0"]["kernel"])
    np.testing.assert_array_equal(k, sd["features.conv0.weight"].transpose(2, 3, 1, 0))
    # BN scale <- weight, batch stats <- running stats
    np.testing.assert_array_equal(
        np.asarray(new_params[0]["norm0"]["scale"]), sd["features.norm0.weight"]
    )
    np.testing.assert_array_equal(
        np.asarray(new_stats[0]["norm0"]["mean"]), sd["features.norm0.running_mean"]
    )
    # 1000-class torch head skipped: our 5-class head keeps fresh init
    np.testing.assert_array_equal(
        np.asarray(new_params[-1]["classifier"]["kernel"]),
        np.asarray(params[-1]["classifier"]["kernel"]),
    )


# ---------------------------------------------------------------------------
# Real-layout validation (VERDICT round 1, Missing #1): the fixtures above are
# built by inverting our own key mapping, so a systematic naming/transpose bug
# would cancel out.  The tests below break that circularity without network
# access (no pretrained download): the key manifest is written from
# torchvision's *published* naming/shapes, and forward parity is checked
# against an independent functional-torch DenseNet evaluated straight off the
# state dict (reference builds exactly this model: ``single.py:297-299``).
# ---------------------------------------------------------------------------

DN121 = dict(growth=32, blocks=(6, 12, 24, 16), init_features=64, bn_size=4)


def _torchvision_densenet121_manifest() -> dict[str, tuple]:
    """torchvision densenet121 state_dict keys -> shapes, generated from the
    published architecture constants — independent of ddl_tpu code."""
    g, blocks, ninit, bn = (
        DN121["growth"], DN121["blocks"], DN121["init_features"], DN121["bn_size"]
    )
    keys: dict[str, tuple] = {}

    def bnorm(prefix, c):
        keys[f"{prefix}.weight"] = (c,)
        keys[f"{prefix}.bias"] = (c,)
        keys[f"{prefix}.running_mean"] = (c,)
        keys[f"{prefix}.running_var"] = (c,)
        keys[f"{prefix}.num_batches_tracked"] = ()

    keys["features.conv0.weight"] = (ninit, 3, 7, 7)
    bnorm("features.norm0", ninit)
    c = ninit
    for b, n_layers in enumerate(blocks, start=1):
        for layer in range(1, n_layers + 1):
            cin = c + (layer - 1) * g
            p = f"features.denseblock{b}.denselayer{layer}"
            bnorm(f"{p}.norm1", cin)
            keys[f"{p}.conv1.weight"] = (bn * g, cin, 1, 1)
            bnorm(f"{p}.norm2", bn * g)
            keys[f"{p}.conv2.weight"] = (g, bn * g, 3, 3)
        c += n_layers * g
        if b < len(blocks):
            bnorm(f"features.transition{b}.norm", c)
            keys[f"features.transition{b}.conv.weight"] = (c // 2, c, 1, 1)
            c //= 2
    bnorm("features.norm5", c)
    keys["classifier.weight"] = (1000, c)
    keys["classifier.bias"] = (1000,)
    return keys


def _random_real_sd(manifest, seed=0):
    """Fill the real manifest with bounded random values (kaiming-ish conv
    scales keep 121 layers of activations finite in float32)."""
    rng = np.random.default_rng(seed)
    sd = {}
    for key, shape in manifest.items():
        if key.endswith("num_batches_tracked"):
            sd[key] = np.asarray(100, np.int64)
        elif key.endswith("running_var"):
            sd[key] = rng.uniform(0.5, 1.5, shape).astype(np.float32)
        elif key.endswith("running_mean"):
            sd[key] = rng.normal(0, 0.1, shape).astype(np.float32)
        elif ".weight" in key and len(shape) == 4:
            fan_in = shape[1] * shape[2] * shape[3]
            sd[key] = rng.normal(0, (2.0 / fan_in) ** 0.5, shape).astype(np.float32)
        elif key == "classifier.weight":
            sd[key] = rng.normal(0, shape[1] ** -0.5, shape).astype(np.float32)
        else:  # bn weight/bias, classifier bias
            sd[key] = (
                rng.uniform(0.5, 1.5, shape) if key.endswith("norm.weight")
                or ".weight" in key else rng.normal(0, 0.1, shape)
            ).astype(np.float32)
    return sd


def _torch_densenet121_forward(sd, x_nchw):
    """Functional-torch DenseNet121 evaluated directly off the state dict
    (mirrors the published torchvision forward; independent of our Flax)."""
    import torch
    import torch.nn.functional as F

    t = {k: torch.as_tensor(v) for k, v in sd.items()}

    def bn(x, p):
        return F.batch_norm(
            x, t[p + ".running_mean"], t[p + ".running_var"],
            t[p + ".weight"], t[p + ".bias"], training=False, eps=1e-5,
        )

    x = torch.as_tensor(x_nchw)
    x = F.conv2d(x, t["features.conv0.weight"], stride=2, padding=3)
    x = F.max_pool2d(F.relu(bn(x, "features.norm0")), 3, stride=2, padding=1)
    for b, n_layers in enumerate(DN121["blocks"], start=1):
        feats = [x]
        for layer in range(1, n_layers + 1):
            p = f"features.denseblock{b}.denselayer{layer}"
            inp = torch.cat(feats, 1)
            y = F.conv2d(F.relu(bn(inp, p + ".norm1")), t[p + ".conv1.weight"])
            y = F.conv2d(
                F.relu(bn(y, p + ".norm2")), t[p + ".conv2.weight"], padding=1
            )
            feats.append(y)
        x = torch.cat(feats, 1)
        if b < len(DN121["blocks"]):
            p = f"features.transition{b}"
            x = F.conv2d(F.relu(bn(x, p + ".norm")), t[p + ".conv.weight"])
            x = F.avg_pool2d(x, 2, stride=2)
    x = F.relu(bn(x, "features.norm5"))
    x = F.adaptive_avg_pool2d(x, 1).flatten(1)
    return (
        F.linear(x, t["classifier.weight"], t["classifier.bias"]).numpy()
    )


@pytest.fixture(scope="module")
def full_staged_1000():
    """Full densenet121 with the 1000-class torch head (so every tensor,
    classifier included, must convert)."""
    from ddl_tpu.config import ModelConfig

    cfg = ModelConfig(num_classes=1000, split_blocks=(), remat=False)
    stages = build_stages(cfg, num_stages=1)
    params, batch_stats = init_stages(stages, jax.random.key(0), image_size=64)
    return stages, params, batch_stats


def test_real_layout_key_parity(full_staged_1000):
    """Our tree's torch-key image must equal torchvision's documented key
    set exactly (minus the stats-only num_batches_tracked counters)."""
    _, params, batch_stats = full_staged_1000
    ours = set()
    for tree in (*params, *batch_stats):
        for path, _ in jax.tree_util.tree_flatten_with_path(tree)[0]:
            ours.add(_torch_key(path, is_stats=False))
    manifest = {
        k for k in _torchvision_densenet121_manifest()
        if not k.endswith("num_batches_tracked")
    }
    assert ours == manifest, (
        f"missing from ours: {sorted(manifest - ours)[:5]} | "
        f"extra in ours: {sorted(ours - manifest)[:5]}"
    )


def test_real_layout_forward_parity(full_staged_1000, tmp_path):
    """Converted-Flax forward == functional-torch forward on the same real
    state dict, to float tolerance — catches any transpose/key bug on the
    genuine torchvision layout."""
    torch = pytest.importorskip("torch")

    from ddl_tpu.models import forward_stages
    from ddl_tpu.models.convert import load_torch_checkpoint

    stages, params, batch_stats = full_staged_1000
    sd = _random_real_sd(_torchvision_densenet121_manifest())
    pth = tmp_path / "dn121.pth"
    torch.save({k: torch.as_tensor(v) for k, v in sd.items()}, pth)

    new_params, new_stats, skipped = load_torch_checkpoint(
        str(pth), params, batch_stats
    )
    assert skipped == [], f"unconverted tensors: {skipped[:5]}"

    rng = np.random.default_rng(7)
    x = rng.normal(0, 1, (2, 64, 64, 3)).astype(np.float32)
    import jax.numpy as jnp

    ours, _ = forward_stages(
        stages, new_params, new_stats, jnp.asarray(x), train=False
    )
    theirs = _torch_densenet121_forward(sd, x.transpose(0, 3, 1, 2))
    np.testing.assert_allclose(np.asarray(ours), theirs, rtol=2e-3, atol=2e-3)


def test_converted_model_still_runs(staged, tiny_model_cfg):
    import jax.numpy as jnp

    from ddl_tpu.models import forward_stages

    stages, params, batch_stats = staged
    sd = _fake_torch_sd(params, batch_stats)
    new_params, new_stats, _ = convert_torch_state_dict(sd, params, batch_stats)
    x = jnp.ones((2, 16, 16, 3), jnp.float32)
    logits, _ = forward_stages(stages, new_params, new_stats, x, train=False)
    assert logits.shape == (2, 5) and bool(jnp.isfinite(logits).all())
