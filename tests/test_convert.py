"""torchvision state-dict conversion: key mapping, transposes, head swap."""

import jax
import numpy as np
import pytest

from ddl_tpu.models import build_stages, init_stages
from ddl_tpu.models.convert import _torch_key, convert_torch_state_dict


@pytest.fixture(scope="module")
def staged(tiny_model_cfg):
    stages = build_stages(tiny_model_cfg)
    params, batch_stats = init_stages(stages, jax.random.key(0), image_size=16)
    return stages, params, batch_stats


def _fake_torch_sd(params, batch_stats, num_classes_torch=1000):
    """Build a torch-style state dict shaped to match our tree (values
    deterministic per key so conversion can be verified)."""
    sd = {}
    for tree in (*params, *batch_stats):
        for path, leaf in jax.tree_util.tree_flatten_with_path(tree)[0]:
            key = _torch_key(path, is_stats=False)
            arr = np.asarray(leaf)
            if arr.ndim == 4:
                shape = (arr.shape[3], arr.shape[2], arr.shape[0], arr.shape[1])
            elif arr.ndim == 2:
                if "classifier" in key:
                    shape = (num_classes_torch, arr.shape[0])  # ImageNet head
                else:
                    shape = arr.shape[::-1]
            else:
                if "classifier" in key:
                    shape = (num_classes_torch,)
                else:
                    shape = arr.shape
            rng = np.random.default_rng(abs(hash(key)) % 2**32)
            val = rng.normal(size=shape).astype(np.float32)
            if key.endswith("running_var"):
                val = np.abs(val) + 0.5  # variances must be positive
            sd[key] = val
    return sd


def test_key_mapping():
    import jax.tree_util as jtu

    p = (jtu.DictKey("denseblock1"), jtu.DictKey("denselayer2"), jtu.DictKey("conv1"), jtu.DictKey("kernel"))
    assert _torch_key(p, False) == "features.denseblock1.denselayer2.conv1.weight"
    p2 = (jtu.DictKey("norm0"), jtu.DictKey("scale"))
    assert _torch_key(p2, False) == "features.norm0.weight"
    p3 = (jtu.DictKey("classifier"), jtu.DictKey("kernel"))
    assert _torch_key(p3, False) == "classifier.weight"
    p4 = (jtu.DictKey("transition1"), jtu.DictKey("norm"), jtu.DictKey("mean"))
    assert _torch_key(p4, False) == "features.transition1.norm.running_mean"


def test_conversion_overlays_and_transposes(staged):
    stages, params, batch_stats = staged
    sd = _fake_torch_sd(params, batch_stats)
    new_params, new_stats, skipped = convert_torch_state_dict(sd, params, batch_stats)

    # every non-classifier tensor must be overlaid
    assert all("classifier" in k for k in skipped), skipped
    # conv kernel transposed OIHW->HWIO
    k = np.asarray(new_params[0]["conv0"]["kernel"])
    np.testing.assert_array_equal(k, sd["features.conv0.weight"].transpose(2, 3, 1, 0))
    # BN scale <- weight, batch stats <- running stats
    np.testing.assert_array_equal(
        np.asarray(new_params[0]["norm0"]["scale"]), sd["features.norm0.weight"]
    )
    np.testing.assert_array_equal(
        np.asarray(new_stats[0]["norm0"]["mean"]), sd["features.norm0.running_mean"]
    )
    # 1000-class torch head skipped: our 5-class head keeps fresh init
    np.testing.assert_array_equal(
        np.asarray(new_params[-1]["classifier"]["kernel"]),
        np.asarray(params[-1]["classifier"]["kernel"]),
    )


def test_converted_model_still_runs(staged, tiny_model_cfg):
    import jax.numpy as jnp

    from ddl_tpu.models import forward_stages

    stages, params, batch_stats = staged
    sd = _fake_torch_sd(params, batch_stats)
    new_params, new_stats, _ = convert_torch_state_dict(sd, params, batch_stats)
    x = jnp.ones((2, 16, 16, 3), jnp.float32)
    logits, _ = forward_stages(stages, new_params, new_stats, x, train=False)
    assert logits.shape == (2, 5) and bool(jnp.isfinite(logits).all())
