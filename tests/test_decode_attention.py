"""Pallas decode-attention kernel (ops/decode_attention.py) vs the XLA
reference — including MULTI-TILE caches (the online-softmax accumulator
path across L tiles, which the generator tests' tiny caches never split).
Interpreter mode on CPU; the identical program compiles on TPU (chip
rates in PERF.md round 5)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ddl_tpu.ops.attention import dense_attention
from ddl_tpu.ops.decode_attention import (
    decode_attention,
    quant_decode_attention,
)
from ddl_tpu.ops.quant import kv_fuse, quantize_q8


def _mk(b=2, L=16, h=8, hkv=4, d=16, seed=0):
    rng = np.random.default_rng(seed)
    q = jnp.asarray(rng.normal(size=(b, 1, h, d)), jnp.float32)
    k = jnp.asarray(rng.normal(size=(b, L, hkv, d)), jnp.float32)
    v = jnp.asarray(rng.normal(size=(b, L, hkv, d)), jnp.float32)
    mask = jnp.asarray(rng.random((1, L)) > 0.3).at[:, 0].set(True)
    return q, k, v, mask


@pytest.mark.parametrize("block_l", [None, 4], ids=["one-tile", "4-tiles"])
def test_kernel_matches_dense(block_l):
    q, k, v, mask = _mk()
    bias = jnp.where(mask, 0.0, -1e30).astype(jnp.float32)
    got = decode_attention(
        q, kv_fuse(k), kv_fuse(v), bias, hkv=4, block_l=block_l,
        interpret=True,
    )
    want = dense_attention(q, k, v, mask=mask)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), atol=2e-5, rtol=1e-4
    )


@pytest.mark.parametrize("block_l", [None, 4], ids=["one-tile", "4-tiles"])
def test_quant_kernel_matches_dequantized(block_l):
    q, k, v, mask = _mk(seed=1)
    bias = jnp.where(mask, 0.0, -1e30).astype(jnp.float32)
    kq, ks = quantize_q8(k)
    vq, vs = quantize_q8(v)
    got = quant_decode_attention(
        q, kv_fuse(kq), ks[..., 0].transpose(0, 2, 1),
        kv_fuse(vq), vs[..., 0].transpose(0, 2, 1), bias,
        hkv=4, block_l=block_l, interpret=True,
    )
    from ddl_tpu.ops.quant import dequantize_q8

    want = dense_attention(
        q, dequantize_q8(kq, ks), dequantize_q8(vq, vs), mask=mask
    )
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), atol=2e-5, rtol=1e-4
    )


def test_kernel_mha_and_fully_masked_tile():
    """MHA (hkv == h) and a bias whose whole LAST tile is masked — the
    accumulator must ignore it (exp-zeroed rows), not poison the output."""
    q, k, v, _ = _mk(h=4, hkv=4, seed=2)
    L = k.shape[1]
    mask = jnp.ones((1, L), bool).at[:, L // 2:].set(False)
    bias = jnp.where(mask, 0.0, -1e30).astype(jnp.float32)
    got = decode_attention(
        q, kv_fuse(k), kv_fuse(v), bias, hkv=4, block_l=L // 2,
        interpret=True,
    )
    want = dense_attention(q, k, v, mask=mask)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), atol=2e-5, rtol=1e-4
    )
