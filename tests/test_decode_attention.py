"""Pallas decode-attention kernel (ops/decode_attention.py) vs the XLA
reference — including MULTI-TILE caches (the online-softmax accumulator
path across L tiles, which the generator tests' tiny caches never split).
Interpreter mode on CPU; the identical program compiles on TPU (chip
rates in PERF.md round 5)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ddl_tpu.ops.attention import dense_attention
from ddl_tpu.ops.decode_attention import (
    decode_attention,
    quant_decode_attention,
)
from ddl_tpu.ops.quant import kv_fuse, quantize_q8


def _mk(b=2, L=16, h=8, hkv=4, d=16, seed=0):
    rng = np.random.default_rng(seed)
    q = jnp.asarray(rng.normal(size=(b, 1, h, d)), jnp.float32)
    k = jnp.asarray(rng.normal(size=(b, L, hkv, d)), jnp.float32)
    v = jnp.asarray(rng.normal(size=(b, L, hkv, d)), jnp.float32)
    mask = jnp.asarray(rng.random((1, L)) > 0.3).at[:, 0].set(True)
    return q, k, v, mask


@pytest.mark.parametrize("block_l", [None, 4], ids=["one-tile", "4-tiles"])
def test_kernel_matches_dense(block_l):
    q, k, v, mask = _mk()
    bias = jnp.where(mask, 0.0, -1e30).astype(jnp.float32)
    got = decode_attention(
        q, kv_fuse(k), kv_fuse(v), bias, hkv=4, block_l=block_l,
        interpret=True,
    )
    want = dense_attention(q, k, v, mask=mask)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), atol=2e-5, rtol=1e-4
    )


@pytest.mark.parametrize("block_l", [None, 4], ids=["one-tile", "4-tiles"])
def test_quant_kernel_matches_dequantized(block_l):
    q, k, v, mask = _mk(seed=1)
    bias = jnp.where(mask, 0.0, -1e30).astype(jnp.float32)
    kq, ks = quantize_q8(k)
    vq, vs = quantize_q8(v)
    got = quant_decode_attention(
        q, kv_fuse(kq), ks[..., 0].transpose(0, 2, 1),
        kv_fuse(vq), vs[..., 0].transpose(0, 2, 1), bias,
        hkv=4, block_l=block_l, interpret=True,
    )
    from ddl_tpu.ops.quant import dequantize_q8

    want = dense_attention(
        q, dequantize_q8(kq, ks), dequantize_q8(vq, vs), mask=mask
    )
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), atol=2e-5, rtol=1e-4
    )


def test_kernel_mha_and_fully_masked_tile():
    """MHA (hkv == h) and a bias whose whole LAST tile is masked — the
    accumulator must ignore it (exp-zeroed rows), not poison the output."""
    q, k, v, _ = _mk(h=4, hkv=4, seed=2)
    L = k.shape[1]
    mask = jnp.ones((1, L), bool).at[:, L // 2:].set(False)
    bias = jnp.where(mask, 0.0, -1e30).astype(jnp.float32)
    got = decode_attention(
        q, kv_fuse(k), kv_fuse(v), bias, hkv=4, block_l=L // 2,
        interpret=True,
    )
    want = dense_attention(q, k, v, mask=mask)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), atol=2e-5, rtol=1e-4
    )


def test_pick_block_l_fallback_capped_at_tile_budget():
    """Awkward L (no 128-multiple divisor): the single full-L tile is
    used only within the compile-probed per-tile budget; above it the
    caller must keep the XLA einsum path (ADVICE round 5)."""
    from ddl_tpu.ops.decode_attention import _TILE_BYTES, pick_block_l

    fused = 768  # 12 heads x 64, the probed width
    # no aligned divisor, single tile within budget -> full-L tile
    assert pick_block_l(2200, fused) == 2200
    assert 2200 * fused * 2 <= _TILE_BYTES
    # no aligned divisor, single tile over budget -> None (einsum path);
    # the old relaxed 2x budget admitted these and risked scoped-VMEM
    # compile failures at runtime
    for L in (2500, 3000, 4500):
        assert pick_block_l(L, fused) is None, L
    # aligned divisors keep tiling regardless of L
    assert pick_block_l(4096, fused) in (1024, 2048)


def test_explicit_block_l_respects_mosaic_alignment():
    """Explicit block_l on the compiled (non-interpret) path: partial
    tiles step down in 128-multiples, and an unalignable request raises
    a descriptive error instead of an opaque Mosaic one (ADVICE round 5)."""
    import pytest

    from ddl_tpu.ops.decode_attention import _block_l

    # 128-multiple divisor found by stepping down (512 -> 256 for L=1280)
    assert _block_l(1280, 512, 768, 2, interpret=False) == 256
    assert _block_l(1024, 512, 768, 2, interpret=False) == 512
    # block_l >= L: the full array is always alignment-legal
    assert _block_l(1000, 1000, 768, 2, interpret=False) == 1000
    assert _block_l(1000, 2048, 768, 2, interpret=False) == 1000
    # L=1000 with block_l=512 must NOT land on the unaligned 500
    with pytest.raises(ValueError, match="128-multiple"):
        _block_l(1000, 512, 768, 2, interpret=False)
    # the interpreter has no alignment rules: tiny test tiles still work
    assert _block_l(16, 4, 64, 2, interpret=True) == 4
