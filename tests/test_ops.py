"""Ops: normalize (jnp + pallas-interpret parity), loss functions."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ddl_tpu.ops import cross_entropy_loss, normalize_images, softmax_cross_entropy


def test_normalize_range_and_dtype():
    imgs = np.array([[[[0, 128, 255]]]], np.uint8)
    out = normalize_images(jnp.asarray(imgs), jnp.float32)
    np.testing.assert_allclose(np.asarray(out), [[[[0.0, 128 / 255, 1.0]]]], atol=1e-7)
    assert out.dtype == jnp.float32


def test_pallas_normalize_matches_reference():
    from ddl_tpu.ops.pallas_image import pallas_normalize_images

    rng = np.random.default_rng(0)
    imgs = jnp.asarray(rng.integers(0, 255, (4, 16, 16, 3)), jnp.uint8)
    got = pallas_normalize_images(imgs, jnp.float32, interpret=True)
    want = normalize_images(imgs, jnp.float32)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=1e-7)


def test_pallas_normalize_nondivisible_block():
    from ddl_tpu.ops.pallas_image import pallas_normalize_images

    rng = np.random.default_rng(1)
    # F = 10*10*3 = 300, not a multiple of the 1536 block
    imgs = jnp.asarray(rng.integers(0, 255, (2, 10, 10, 3)), jnp.uint8)
    got = pallas_normalize_images(imgs, jnp.float32, interpret=True)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(normalize_images(imgs, jnp.float32)), atol=1e-7
    )


def test_cross_entropy_matches_torch():
    torch = pytest.importorskip("torch")
    rng = np.random.default_rng(0)
    logits = rng.normal(size=(32, 5)).astype(np.float32)
    labels = rng.integers(0, 5, 32)
    want = torch.nn.functional.cross_entropy(
        torch.tensor(logits), torch.tensor(labels)
    ).item()
    got = float(cross_entropy_loss(jnp.asarray(logits), jnp.asarray(labels)))
    assert got == pytest.approx(want, rel=1e-5)


def test_softmax_cross_entropy_gradient_is_softmax_minus_onehot():
    logits = jnp.asarray([[2.0, 1.0, 0.0, -1.0, 0.5]])
    labels = jnp.asarray([2])
    g = jax.grad(lambda l: softmax_cross_entropy(l, labels).sum())(logits)
    p = np.exp(np.asarray(logits[0]))
    p /= p.sum()
    p[2] -= 1
    np.testing.assert_allclose(np.asarray(g[0]), p, atol=1e-6)


class TestGroupedDenseAttention:
    def test_grouped_matches_repeated_kv(self):
        """GQA grouping == materially repeating each K/V head over its
        query group (the definition), causal and masked variants."""
        from ddl_tpu.ops.attention import dense_attention

        rng = np.random.default_rng(0)
        b, t, h, hkv, d = 2, 8, 6, 2, 4
        q = jnp.asarray(rng.normal(size=(b, t, h, d)), jnp.float32)
        k = jnp.asarray(rng.normal(size=(b, t, hkv, d)), jnp.float32)
        v = jnp.asarray(rng.normal(size=(b, t, hkv, d)), jnp.float32)
        grouped = dense_attention(q, k, v, causal=True)
        repeated = dense_attention(
            q, jnp.repeat(k, h // hkv, 2), jnp.repeat(v, h // hkv, 2),
            causal=True,
        )
        np.testing.assert_allclose(
            np.asarray(grouped), np.asarray(repeated), atol=1e-6
        )

    def test_indivisible_heads_raise(self):
        from ddl_tpu.ops.attention import dense_attention

        q = jnp.zeros((1, 4, 6, 4))
        kv = jnp.zeros((1, 4, 4, 4))
        with pytest.raises(ValueError, match="divide"):
            dense_attention(q, kv, kv, causal=True)


class TestFusedChunkedCE:
    """Chunked head+CE fusion (ops/losses.fused_chunked_ce): exact parity
    with head-matmul + dense CE, in values AND gradients, without ever
    materialising (B, T, V) logits (VERDICT round 2, task 3)."""

    def _setup(self):
        import jax.numpy as jnp

        rng = np.random.default_rng(0)
        b, t, d, v = 2, 32, 16, 97  # odd vocab: no tiling luck
        h = jnp.asarray(rng.normal(size=(b, t, d)), jnp.float32)
        # vocab-major kernel, as LMHead stores it
        w = jnp.asarray(rng.normal(size=(v, d)) * 0.1, jnp.float32)
        tg = jnp.asarray(rng.integers(0, v, (b, t)))
        return h, w, tg

    def _dense(self, h, w, tg):
        from ddl_tpu.ops.losses import cross_entropy_loss

        return cross_entropy_loss(h.astype(np.float32) @ w.T, tg)

    @pytest.mark.parametrize("chunk", [4, 8, 32, 100])
    @pytest.mark.parametrize("use_onehot", [False, True])
    def test_value_and_grad_parity(self, chunk, use_onehot):
        import jax
        import jax.numpy as jnp

        from ddl_tpu.ops.losses import fused_chunked_ce

        h, w, tg = self._setup()
        ce, acc = fused_chunked_ce(
            h, w, tg, chunk, with_accuracy=True, use_onehot=use_onehot
        )
        want = self._dense(h, w, tg)
        np.testing.assert_allclose(float(ce), float(want), atol=1e-5)
        logits = np.asarray(h) @ np.asarray(w).T
        np.testing.assert_allclose(
            float(acc), float(np.mean(logits.argmax(-1) == np.asarray(tg))),
            atol=1e-7,
        )
        gh, gw = jax.grad(
            lambda a, b: fused_chunked_ce(a, b, tg, chunk,
                                          use_onehot=use_onehot)[0],
            (0, 1),
        )(h, w)
        rh, rw = jax.grad(lambda a, b: self._dense(a, b, tg), (0, 1))(h, w)
        np.testing.assert_allclose(np.asarray(gh), np.asarray(rh), atol=1e-5)
        np.testing.assert_allclose(np.asarray(gw), np.asarray(rw), atol=1e-5)

    def test_rejects_bad_chunk(self):
        from ddl_tpu.ops.losses import fused_chunked_ce

        h, w, tg = self._setup()
        with pytest.raises(ValueError, match="token_chunk"):
            fused_chunked_ce(h, w, tg, 0)

    def test_non_divisor_chunk_warns_and_picks_largest_divisor(self):
        import warnings

        from ddl_tpu.ops.losses import fused_chunked_ce

        h, w, tg = self._setup()  # T=32
        with warnings.catch_warnings(record=True) as rec:
            warnings.simplefilter("always")
            ce, _ = fused_chunked_ce(h, w, tg, 24)  # largest divisor: 16
        assert any("largest divisor 16" in str(r.message) for r in rec)
        np.testing.assert_allclose(
            float(ce), float(self._dense(h, w, tg)), atol=1e-5
        )


class TestFusedVocabChunkedCE:
    """Vocab-streamed head+CE (ops/losses.fused_vocab_chunked_ce): exact
    value/grad/accuracy parity with dense CE while the (B, T, V) logits
    never exist in either direction (the extreme-vocab loss edge; PERF.md
    round 4 records it ~5% slower than dense at V=50k b=16 — the lever
    is memory, not rate)."""

    def _setup(self):
        import jax.numpy as jnp

        rng = np.random.default_rng(1)
        b, t, d, v = 2, 24, 12, 90
        h = jnp.asarray(rng.normal(size=(b, t, d)), jnp.float32)
        w = jnp.asarray(rng.normal(size=(v, d)) * 0.1, jnp.float32)
        tg = jnp.asarray(rng.integers(0, v, (b, t)))
        return h, w, tg

    def _dense(self, h, w, tg):
        from ddl_tpu.ops.losses import cross_entropy_loss

        return cross_entropy_loss(h.astype(np.float32) @ w.T, tg)

    @pytest.mark.parametrize("vb", [15, 30, 90, 1000])
    def test_value_grad_and_accuracy_parity(self, vb):
        import jax

        from ddl_tpu.ops.losses import fused_vocab_chunked_ce

        h, w, tg = self._setup()
        ce, acc = fused_vocab_chunked_ce(h, w, tg, vb, True)
        np.testing.assert_allclose(
            float(ce), float(self._dense(h, w, tg)), atol=1e-5
        )
        logits = np.asarray(h) @ np.asarray(w).T
        np.testing.assert_allclose(
            float(acc), float(np.mean(logits.argmax(-1) == np.asarray(tg))),
            atol=1e-7,
        )
        gh, gw = jax.grad(
            lambda a, b: fused_vocab_chunked_ce(a, b, tg, vb)[0], (0, 1)
        )(h, w)
        rh, rw = jax.grad(lambda a, b: self._dense(a, b, tg), (0, 1))(h, w)
        np.testing.assert_allclose(np.asarray(gh), np.asarray(rh), atol=1e-5)
        np.testing.assert_allclose(np.asarray(gw), np.asarray(rw), atol=1e-5)

    def test_upstream_gradient_scales(self):
        """The custom VJP must respect a non-unit upstream cotangent."""
        import jax

        from ddl_tpu.ops.losses import fused_vocab_chunked_ce

        h, w, tg = self._setup()
        g3 = jax.grad(
            lambda a: 3.0 * fused_vocab_chunked_ce(a, w, tg, 30)[0]
        )(h)
        g1 = jax.grad(
            lambda a: fused_vocab_chunked_ce(a, w, tg, 30)[0]
        )(h)
        np.testing.assert_allclose(
            np.asarray(g3), 3 * np.asarray(g1), rtol=1e-5
        )
