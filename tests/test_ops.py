"""Ops: normalize (jnp + pallas-interpret parity), loss functions."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ddl_tpu.ops import cross_entropy_loss, normalize_images, softmax_cross_entropy


def test_normalize_range_and_dtype():
    imgs = np.array([[[[0, 128, 255]]]], np.uint8)
    out = normalize_images(jnp.asarray(imgs), jnp.float32)
    np.testing.assert_allclose(np.asarray(out), [[[[0.0, 128 / 255, 1.0]]]], atol=1e-7)
    assert out.dtype == jnp.float32


def test_pallas_normalize_matches_reference():
    from ddl_tpu.ops.pallas_image import pallas_normalize_images

    rng = np.random.default_rng(0)
    imgs = jnp.asarray(rng.integers(0, 255, (4, 16, 16, 3)), jnp.uint8)
    got = pallas_normalize_images(imgs, jnp.float32, interpret=True)
    want = normalize_images(imgs, jnp.float32)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=1e-7)


def test_pallas_normalize_nondivisible_block():
    from ddl_tpu.ops.pallas_image import pallas_normalize_images

    rng = np.random.default_rng(1)
    # F = 10*10*3 = 300, not a multiple of the 1536 block
    imgs = jnp.asarray(rng.integers(0, 255, (2, 10, 10, 3)), jnp.uint8)
    got = pallas_normalize_images(imgs, jnp.float32, interpret=True)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(normalize_images(imgs, jnp.float32)), atol=1e-7
    )


def test_cross_entropy_matches_torch():
    torch = pytest.importorskip("torch")
    rng = np.random.default_rng(0)
    logits = rng.normal(size=(32, 5)).astype(np.float32)
    labels = rng.integers(0, 5, 32)
    want = torch.nn.functional.cross_entropy(
        torch.tensor(logits), torch.tensor(labels)
    ).item()
    got = float(cross_entropy_loss(jnp.asarray(logits), jnp.asarray(labels)))
    assert got == pytest.approx(want, rel=1e-5)


def test_softmax_cross_entropy_gradient_is_softmax_minus_onehot():
    logits = jnp.asarray([[2.0, 1.0, 0.0, -1.0, 0.5]])
    labels = jnp.asarray([2])
    g = jax.grad(lambda l: softmax_cross_entropy(l, labels).sum())(logits)
    p = np.exp(np.asarray(logits[0]))
    p /= p.sum()
    p[2] -= 1
    np.testing.assert_allclose(np.asarray(g[0]), p, atol=1e-6)


class TestGroupedDenseAttention:
    def test_grouped_matches_repeated_kv(self):
        """GQA grouping == materially repeating each K/V head over its
        query group (the definition), causal and masked variants."""
        from ddl_tpu.ops.attention import dense_attention

        rng = np.random.default_rng(0)
        b, t, h, hkv, d = 2, 8, 6, 2, 4
        q = jnp.asarray(rng.normal(size=(b, t, h, d)), jnp.float32)
        k = jnp.asarray(rng.normal(size=(b, t, hkv, d)), jnp.float32)
        v = jnp.asarray(rng.normal(size=(b, t, hkv, d)), jnp.float32)
        grouped = dense_attention(q, k, v, causal=True)
        repeated = dense_attention(
            q, jnp.repeat(k, h // hkv, 2), jnp.repeat(v, h // hkv, 2),
            causal=True,
        )
        np.testing.assert_allclose(
            np.asarray(grouped), np.asarray(repeated), atol=1e-6
        )

    def test_indivisible_heads_raise(self):
        from ddl_tpu.ops.attention import dense_attention

        q = jnp.zeros((1, 4, 6, 4))
        kv = jnp.zeros((1, 4, 4, 4))
        with pytest.raises(ValueError, match="divide"):
            dense_attention(q, kv, kv, causal=True)
