"""Streaming obs engine (PR 8): the incremental fold engine
(``obs/fold.py``), mergeable t-digest serving percentiles, cross-host
clock-skew estimation, ``obs watch``/``obs export``, and the
``restart_latency`` event + gate.

The load-bearing property: ``fold_job`` with its sidecar must render
``obs summarize`` and ``obs pod`` BYTE-IDENTICALLY to a cold full parse
(``cache=False``) under arbitrary append/torn-line/truncate/recreate
histories, while reading only the appended bytes.
"""

import json
import os

import numpy as np
import pytest

# ---------------------------------------------------------------------------
# synthetic streams
# ---------------------------------------------------------------------------


def _ev(host, kind, ts, **kw):
    e = {
        "ts": ts, "mono": ts, "run": f"r{host}", "host": host,
        "step": kw.pop("step", None), "kind": kind,
    }
    e.update(kw)
    return e


def _rich_events(host, *, offset=0.0, periods=4, step_s=0.10):
    """One host's event list exercising every fold reducer: periods
    (two restart epochs), spans, barriers with completion stamps,
    warm+cold decode, serve counters, anomalies/stalls/captures, and a
    restart_latency.  ``offset`` shifts the host's clock (skew)."""
    evs = [_ev(host, "run_start", 1.0 + offset, family="lm")]
    for p in range(periods):
        repoch = 0 if p < periods - 1 else 1
        sps = 10.0 / (1 + 0.05 * host)
        evs.append(_ev(
            host, "period", 10.0 + p + offset, step=p, period=p,
            steps=10, elapsed=1.0 + 0.1 * host, steps_per_sec=sps,
            phases={"step": step_s * 10, "data_wait": 0.2, "fence": 0.01},
            compiles=1 if p == 0 else 0, hbm_peak_bytes=1e9 + host,
            loss=2.0 - 0.1 * p, **({"repoch": repoch} if repoch else {}),
        ))
    evs.append(_ev(
        host, "span", 20.0 + offset, step=40, name="dispatch", dur=0.4,
        depth=0,
    ))
    evs.append(_ev(host, "heartbeat", 21.0 + offset, step=41))
    for b, bts in (("start", 30.0), ("e1-join", 40.0)):
        evs.append(_ev(
            host, "coord_barrier", bts + offset + 0.002 * host, name=b,
            wait=0.3 * host, completed_ts=bts + offset,
        ))
    evs.append(_ev(
        host, "decode", 50.0 + offset, prompt_len=8, new_tokens=16,
        batch=1, dur=0.5, queue_delay=0.0, ttft=0.1 + 0.01 * host,
        tok_per_s=32.0, warm=False, chips=2,
    ))
    # two tenant-tagged warm decodes + one untagged (the untagged one
    # folds into the "default" tenant): the split/truncate/recreate
    # equivalence tests below exercise the v9 per-tenant layer through
    # every sidecar history for free
    tags = [
        {"tenant": "acme", "priority_class": "interactive"},
        {"tenant": "bulk", "priority_class": "batch"},
        {},
    ]
    for i in range(3):
        evs.append(_ev(
            host, "decode", 51.0 + i + offset, prompt_len=8,
            new_tokens=16, batch=1, dur=0.4 + 0.1 * i,
            queue_delay=0.01 * i, ttft=0.1, tok_per_s=30.0 + i,
            warm=True, chips=2, **tags[i],
        ))
    evs.append(_ev(
        host, "serve_admit", 55.0 + offset, request_id=1,
        tenant="acme", priority_class="interactive",
    ))
    evs.append(_ev(
        host, "serve_retire", 55.2 + offset, request_id=1,
        tenant="acme", priority_class="interactive",
    ))
    evs.append(_ev(
        host, "serve_shed", 55.5 + offset, request_id=2,
        reason="queue_full", tenant="bulk", priority_class="batch",
    ))
    evs.append(_ev(
        host, "kv_pool_stats", 56.0 + offset, num_blocks=64,
        block_size=8, free=60, used=4, high_water=8, fragmentation=0.0,
        queue_depth=0, active_lanes=1,
    ))
    # HBM ledger kinds (v10): a static plan, two samples (the second is
    # the peak — the paired max cell must carry ITS categories), and on
    # host 1 an OOM forensic dump; the sidecar-history equivalence
    # tests below exercise the hbm reducer through every fold path
    evs.append(_ev(
        host, "hbm_plan", 56.2 + offset, label="train_step",
        analysis="compiled", argument_bytes=1000, output_bytes=1000,
        temp_bytes=200, alias_bytes=900, code_bytes=50,
    ))
    evs.append(_ev(
        host, "hbm_sample", 56.4 + offset, params_bytes=600,
        opt_bytes=1200, watermark=2000, peak=2000, limit=4096,
        synthetic=True,
    ))
    evs.append(_ev(
        host, "hbm_sample", 56.6 + offset, params_bytes=600,
        opt_bytes=1200, kv_cached_bytes=64, kv_private_bytes=32,
        kv_free_bytes=128, watermark=2200 + host, peak=2300 + host,
        limit=4096, synthetic=True,
    ))
    if host == 1:
        evs.append(_ev(
            host, "hbm_oom_dump", 56.8 + offset, step=9,
            error="RESOURCE_EXHAUSTED: out of memory", watermark=4000,
            limit=4096,
            buffers=[{"shape": [64, 64], "dtype": "float32",
                      "count": 2, "bytes": 32768}],
        ))
    if host == 0:
        evs.append(_ev(
            host, "anomaly", 60.0 + offset, step=2, type="loss_spike",
            value=9.9, baseline=1.0,
        ))
        evs.append(_ev(
            host, "profile_capture", 61.0 + offset, step=2, ok=True,
            trigger="loss_spike", trace_dir="/tmp/x",
            digest={"ops": {"dot": 1.0}, "top_op": "dot.3"},
        ))
    if host == 1:
        evs.append(_ev(
            host, "stall", 62.0 + offset, step=33, age=5.0,
            deadline=4.0, stacks={"t1": "tb", "t2": "tb"},
        ))
        evs.append(_ev(
            host, "supervisor_relaunch", 63.0 + offset, reason="preempt",
            rc=75, delay=0.0,
        ))
    evs.append(_ev(
        host, "restart_latency", 70.0 + offset, step=5,
        latency=3.0 + host, decision_ts=67.0, repoch=1,
    ))
    evs.append(_ev(host, "run_end", 80.0 + offset, phases={}, anomalies=0))
    return evs


def _append(log_dir, job, host, lines, torn=None):
    d = log_dir / "by_job_id" / job
    d.mkdir(parents=True, exist_ok=True)
    with open(d / f"events-h{host:03d}.jsonl", "a") as f:
        for ln in lines:
            f.write(ln + "\n")
        if torn is not None:
            f.write(torn)
    return d / f"events-h{host:03d}.jsonl"


def _render_both(log_dir, job, cache):
    from ddl_tpu.obs.fold import fold_job
    from ddl_tpu.obs.pod import pod_summary_from_fold, render_pod_summary
    from ddl_tpu.obs.report import render_summary, summarize_from_fold

    fold = fold_job(log_dir, job, cache=cache)
    return (
        render_summary(summarize_from_fold(fold), job),
        render_pod_summary(pod_summary_from_fold(fold), job),
        fold,
    )


# ---------------------------------------------------------------------------
# incremental-fold equivalence
# ---------------------------------------------------------------------------


def test_fold_equivalence_under_arbitrary_splits(tmp_path):
    """Resumed folds across arbitrary append splits (torn line included)
    render summarize AND pod byte-identically to a cold full parse at
    every intermediate state."""
    from ddl_tpu.obs.fold import SIDECAR_NAME

    job = "eq"
    lines = {
        h: [json.dumps(e) for e in _rich_events(h, offset=0.001 * h)]
        for h in range(3)
    }
    # three slices with uneven per-host boundaries; slice 1 ends in a
    # torn line that slice 2's first write completes
    torn_full = lines[1][7]
    cut = len(torn_full) // 2
    slices = [
        {0: (0, 5, None), 1: (0, 7, torn_full[:cut]), 2: (0, 3, None)},
        {0: (5, 11, None), 2: (3, 9, None)},
        {h: (None, None, None) for h in range(3)},
    ]
    done = {0: 0, 1: 7, 2: 0}
    for i, sl in enumerate(slices):
        for h, (a, b, torn) in sl.items():
            if a is None:
                a, b = done[h], len(lines[h])
            if i == 1 and h == 1:
                pass
            _append(tmp_path, job, h, lines[h][a:b], torn=torn)
            done[h] = b
        if i == 1:
            # complete host 1's torn line, then its remaining events
            _append(tmp_path, job, 1, [], torn=torn_full[cut:] + "\n")
            _append(tmp_path, job, 1, lines[1][8:])
            done[1] = len(lines[1])
        warm_s, warm_p, _ = _render_both(tmp_path, job, cache=True)
        cold_s, cold_p, _ = _render_both(tmp_path, job, cache=False)
        assert warm_s == cold_s, f"summarize diverged at slice {i}"
        assert warm_p == cold_p, f"pod view diverged at slice {i}"
    assert (tmp_path / "by_job_id" / job / SIDECAR_NAME).exists()
    # the final view saw everything
    assert "straggler" in warm_p or "skew" in warm_p
    assert "restart latency: 3 restart(s)" in warm_s


def test_fold_pipe_schedule_cell_and_byte_identity(tmp_path):
    """The pipe_schedule reducer (sidecar v7): last-wins cell, rendered
    as summarize's pipeline line, with warm==cold byte identity across
    a resume that appends a NEWER schedule event (a resumed run can
    change layout)."""
    import json as _json

    job = "sched"
    ev1 = _ev(
        0, "pipe_schedule", 5.0, schedule="1f1b", pipe=2, microbatches=4,
        virtual=1, makespan=14.0, idle_units=4.0, bubble_fraction=0.142857,
        per_stage=[{"F": 4.0, "B": 4.0, "W": 4.0, "idle": 2.0}] * 2,
    )
    _append(tmp_path, job, 0, [_json.dumps(e) for e in (ev1,)])
    _append(tmp_path, job, 0, [_json.dumps(e) for e in _rich_events(0)[:3]])
    warm, _, fold = _render_both(tmp_path, job, cache=True)
    cold, _, _ = _render_both(tmp_path, job, cache=False)
    assert warm == cold
    assert "pipeline: 1f1b pipe=2 microbatches=4" in warm
    assert "modeled bubble 14.3%" in warm
    assert fold.pipe_schedule()["schedule"] == "1f1b"

    # resume with a newer zb event: the cell flips last-wins, warm
    # (resumed sidecar) still byte-identical to cold
    ev2 = dict(ev1, ts=50.0, mono=50.0, schedule="zb", idle_units=2.0,
               bubble_fraction=0.076923, makespan=13.0)
    _append(tmp_path, job, 0, [_json.dumps(ev2)])
    warm2, _, fold2 = _render_both(tmp_path, job, cache=True)
    cold2, _, _ = _render_both(tmp_path, job, cache=False)
    assert warm2 == cold2
    assert "pipeline: zb" in warm2
    assert fold2.pipe_schedule()["schedule"] == "zb"

    # an event without modeled fields (unmodeled combo) still renders
    # the identity half of the line
    job2 = "sched2"
    _append(tmp_path, job2, 0, [_json.dumps(_ev(
        0, "pipe_schedule", 6.0, schedule="1f1b", pipe=2, microbatches=4,
        virtual=2, makespan=None, idle_units=None, bubble_fraction=None,
        per_stage=None,
    ))])
    warm3, _, _ = _render_both(tmp_path, job2, cache=True)
    assert "pipeline: 1f1b pipe=2 microbatches=4 virtual=2" in warm3
    assert "modeled bubble" not in warm3


def test_fold_reads_only_appended_bytes(tmp_path):
    """The O(appended-bytes) acceptance: a resumed fold's read volume is
    bounded by the appended tail (plus the 64-byte head fingerprints),
    not the stream size."""
    job = "bytes"
    lines = {h: [json.dumps(e) for e in _rich_events(h)] for h in range(3)}
    for h in range(3):
        _append(tmp_path, job, h, lines[h][:-2])
    _, _, fold1 = _render_both(tmp_path, job, cache=True)
    total = sum(
        (tmp_path / "by_job_id" / job / f"events-h{h:03d}.jsonl")
        .stat().st_size for h in range(3)
    )
    assert fold1.bytes_read == total  # first fold reads everything

    appended = 0
    for h in range(3):
        tail = lines[h][-2:]
        appended += sum(len(ln) + 1 for ln in tail)
        _append(tmp_path, job, h, tail)
    _, _, fold2 = _render_both(tmp_path, job, cache=True)
    # appended tails + <=64B fingerprint per stream, nothing more
    assert fold2.bytes_read <= appended + 3 * 64
    assert fold2.bytes_read >= appended

    _, _, fold3 = _render_both(tmp_path, job, cache=True)
    assert fold3.bytes_read <= 3 * 64  # nothing appended: heads only


def test_fold_truncation_and_recreation_rebuild(tmp_path):
    """A stream that shrank below its cursor, or was deleted and
    re-created under the same name (even LARGER than the old cursor),
    or disappeared outright: clean rebuild, never double/half counts."""
    job = "trunc"
    lines = [json.dumps(e) for e in _rich_events(0)]
    path = _append(tmp_path, job, 0, lines)
    warm, _, _ = _render_both(tmp_path, job, cache=True)

    # truncate below the cursor
    path.write_text("\n".join(lines[:4]) + "\n")
    warm_s, warm_p, _ = _render_both(tmp_path, job, cache=True)
    cold_s, cold_p, _ = _render_both(tmp_path, job, cache=False)
    assert warm_s == cold_s and warm_p == cold_p

    # recreate under the same name with MORE bytes but different head
    path.unlink()
    other = [json.dumps(e) for e in _rich_events(0, offset=123.0)]
    _append(tmp_path, job, 0, other + other)
    warm_s, _, _ = _render_both(tmp_path, job, cache=True)
    cold_s, _, _ = _render_both(tmp_path, job, cache=False)
    assert warm_s == cold_s

    # a second tracked stream disappearing invalidates too
    extra = _append(tmp_path, job, 1, [json.dumps(e) for e in _rich_events(1)])
    _render_both(tmp_path, job, cache=True)
    extra.unlink()
    warm_s, _, _ = _render_both(tmp_path, job, cache=True)
    cold_s, _, _ = _render_both(tmp_path, job, cache=False)
    assert warm_s == cold_s


def test_fold_corrupt_sidecar_rebuilds(tmp_path):
    """A JSON-valid sidecar with the wrong inner shape is discarded and
    rebuilt in place, not a crash on every summarize."""
    from ddl_tpu.obs.fold import SIDECAR_NAME, VERSION

    job = "corrupt"
    _append(tmp_path, job, 0, [json.dumps(e) for e in _rich_events(0)])
    _render_both(tmp_path, job, cache=True)
    sidecar = tmp_path / "by_job_id" / job / SIDECAR_NAME
    sidecar.write_text(json.dumps({
        "version": VERSION, "capacity": 4096,
        "files": {"events-h000.jsonl": 10},
        "streams": {"events-h000.jsonl": {"bogus": True}},
        "heads": {},
    }))
    warm_s, _, _ = _render_both(tmp_path, job, cache=True)
    cold_s, _, _ = _render_both(tmp_path, job, cache=False)
    assert warm_s == cold_s
    # and the rebuild repaired the sidecar
    warm2, _, fold = _render_both(tmp_path, job, cache=True)
    assert warm2 == cold_s and fold.bytes_read <= 64


def test_summarize_cli_is_incremental_and_identical(tmp_path, capsys):
    """The CLI path end to end: `obs summarize` warm == `--no-cache`
    cold, and the warm path reads only appended bytes (counted through
    the fold the CLI builds)."""
    from ddl_tpu import cli

    job = "cli"
    for h in range(2):
        _append(
            tmp_path, job, h,
            [json.dumps(e) for e in _rich_events(h)],
        )
    cli.main(["obs", "summarize", job, "--log-dir", str(tmp_path)])
    warm = capsys.readouterr().out
    cli.main([
        "obs", "summarize", job, "--log-dir", str(tmp_path), "--no-cache",
    ])
    cold = capsys.readouterr().out
    assert warm == cold
    cli.main(["obs", "pod", job, "--log-dir", str(tmp_path)])
    pod_warm = capsys.readouterr().out
    cli.main(["obs", "pod", job, "--log-dir", str(tmp_path), "--no-cache"])
    pod_cold = capsys.readouterr().out
    assert pod_warm == pod_cold
    assert "clk_off_s" in pod_warm


# ---------------------------------------------------------------------------
# clock-skew estimation
# ---------------------------------------------------------------------------


def test_clock_skew_estimator_recovers_injected_offsets():
    """Synthetic barrier completions with known per-host offsets + small
    observation noise: the least-squares fit recovers the (centered)
    offsets to well under the noise floor."""
    from ddl_tpu.obs.fold import estimate_clock_offsets

    rng = np.random.default_rng(0)
    true = {0: -1.25, 1: 0.0, 2: 2.5}
    center = sum(true.values()) / len(true)
    arrivals = {h: {} for h in true}
    for i in range(12):
        t = 100.0 * i
        for h, off in true.items():
            arrivals[h][f"0:b{i}"] = t + off + float(rng.normal(0, 0.02))
    fit = estimate_clock_offsets(arrivals)
    for h, off in true.items():
        assert fit[h] == pytest.approx(off - center, abs=0.05)

    # degenerate inputs: one host, or no shared key -> None
    assert estimate_clock_offsets({0: {"0:b": 1.0}}) is None
    assert estimate_clock_offsets(
        {0: {"0:a": 1.0}, 1: {"0:b": 2.0}}
    ) is None


def test_skew_corrects_pod_timeline_and_json(tmp_path, capsys):
    """Hosts with skewed clocks: the fitted offsets land in `obs pod
    --json` and the unified timeline re-orders by corrected time."""
    from ddl_tpu import cli
    from ddl_tpu.obs.fold import fold_job
    from ddl_tpu.obs.pod import pod_summary_from_fold

    job = "skewed"
    offsets = {0: 0.0, 1: 5.0, 2: -5.0}  # seconds of clock skew
    for h, off in offsets.items():
        _append(
            tmp_path, job, h,
            [json.dumps(e) for e in _rich_events(h, offset=off)],
        )
    s = pod_summary_from_fold(fold_job(tmp_path, job, cache=False))
    fit = s["clock_offsets"]
    center = sum(offsets.values()) / 3
    for h, off in offsets.items():
        assert fit[h] == pytest.approx(off - center, abs=0.05)
    # corrected timeline: each host's run_start happened at the same
    # true instant; adjusted stamps agree even though raw ts differ by
    # up to 10s
    starts = [
        e for e in s["timeline"] if e["kind"] == "run_start"
    ]
    assert len(starts) == 3
    raw_spread = max(e["ts"] for e in starts) - min(e["ts"] for e in starts)
    adj_spread = (
        max(e["ts_adj"] for e in starts)
        - min(e["ts_adj"] for e in starts)
    )
    assert raw_spread > 9.0 and adj_spread < 0.1

    cli.main(["obs", "pod", job, "--log-dir", str(tmp_path), "--json"])
    parsed = json.loads(capsys.readouterr().out)
    assert parsed["clock_offsets"][str(min(offsets))] == pytest.approx(
        fit[0], abs=1e-9,
    ) or parsed["clock_offsets"]["0"] == pytest.approx(fit[0], abs=1e-9)


# ---------------------------------------------------------------------------
# watch / export surfaces
# ---------------------------------------------------------------------------


def test_watch_once_renders_populated_frame(tmp_path, capsys):
    from ddl_tpu import cli

    job = "watchme"
    for h in range(3):
        _append(
            tmp_path, job, h,
            [json.dumps(e) for e in _rich_events(h)],
        )
    cli.main([
        "obs", "watch", job, "--log-dir", str(tmp_path), "--once",
    ])
    out = capsys.readouterr().out
    assert f"obs watch — {job}" in out
    assert "hosts (latest period)" in out
    assert "phase breakdown" in out
    assert "skew (means over shared periods" in out
    assert "clk_off_s" in out
    assert "requests: 12 (3 cold)" in out
    assert "restart latency: 3 restart(s)" in out
    assert "anomaly:loss_spike" in out
    assert "\x1b" not in out  # --once output is pipe-clean

    with pytest.raises(SystemExit, match="no events"):
        cli.main([
            "obs", "watch", "nosuch", "--log-dir", str(tmp_path), "--once",
        ])


def test_export_prom_golden(tmp_path, capsys):
    from ddl_tpu import cli

    job = "prom"
    for h in range(2):
        _append(
            tmp_path, job, h,
            [json.dumps(e) for e in _rich_events(h)],
        )
    cli.main(["obs", "export", job, "--log-dir", str(tmp_path), "--once"])
    out = capsys.readouterr().out
    # structural golden checks: headers once per metric, deterministic
    # label order, the core series present with the right values
    assert "# TYPE ddl_obs_steps_total counter" in out
    assert (
        f'ddl_obs_steps_total{{host="0",job_id="{job}",repoch="0"}} 30'
        in out
    )
    assert (
        f'ddl_obs_steps_total{{host="0",job_id="{job}",repoch="1"}} 10'
        in out
    )
    assert f'ddl_obs_decode_requests_total{{job_id="{job}"}} 8' in out
    assert 'quantile="0.95"' in out
    assert "ddl_obs_decode_latency_seconds{" in out
    assert (
        f'ddl_obs_restart_latency_seconds{{host="1",job_id="{job}",'
        f'repoch="1"}} 4' in out
    )
    assert f'ddl_obs_kv_free_blocks{{host="0",job_id="{job}"}} 60' in out
    assert "ddl_obs_clock_offset_seconds{" in out
    # emitting twice is identical (deterministic render, incremental fold)
    cli.main(["obs", "export", job, "--log-dir", str(tmp_path), "--once"])
    assert capsys.readouterr().out == out

    # --prom FILE writes the same scrape atomically
    target = tmp_path / "metrics.prom"
    cli.main([
        "obs", "export", job, "--log-dir", str(tmp_path), "--once",
        "--prom", str(target),
    ])
    capsys.readouterr()
    assert target.read_text() == out

    with pytest.raises(SystemExit, match="no events"):
        cli.main([
            "obs", "export", "nosuch", "--log-dir", str(tmp_path),
            "--once",
        ])


def test_export_http_serves_metrics(tmp_path):
    """--http: a real GET /metrics against the threaded server."""
    import threading
    import urllib.request

    from ddl_tpu.obs.export import prometheus_text
    from ddl_tpu.obs.fold import fold_job

    job = "http"
    _append(tmp_path, job, 0, [json.dumps(e) for e in _rich_events(0)])

    # bind port 0 ourselves to avoid collisions; reuse the handler via
    # export's internal server by calling it on a thread
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    def scrape():
        return prometheus_text(fold_job(tmp_path, job, cache=True), job)

    class H(BaseHTTPRequestHandler):
        def do_GET(self):
            body = scrape().encode()
            self.send_response(200)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def log_message(self, *a):
            pass

    srv = ThreadingHTTPServer(("127.0.0.1", 0), H)
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    try:
        port = srv.server_address[1]
        body = urllib.request.urlopen(
            f"http://127.0.0.1:{port}/metrics", timeout=10
        ).read().decode()
        assert "ddl_obs_steps_total{" in body
    finally:
        srv.shutdown()
        srv.server_close()


# ---------------------------------------------------------------------------
# t-digest
# ---------------------------------------------------------------------------


def test_tdigest_exact_in_singleton_regime_matches_numpy():
    from ddl_tpu.obs.serving import TDigest

    rng = np.random.default_rng(1)
    stream = [float(x) for x in rng.exponential(0.2, size=2000)]
    dig = TDigest(exact_max=4096)
    for x in stream:
        dig.add(x)
    for q in (0.0, 0.5, 0.9, 0.95, 0.99, 1.0):
        assert dig.quantile(q) == pytest.approx(
            float(np.quantile(stream, q)), rel=1e-12, abs=1e-12
        )
    assert dig.mean == pytest.approx(float(np.mean(stream)))


def test_tdigest_compressed_tolerance_and_determinism():
    """Past the singleton budget, quantiles stay within a few percent of
    numpy on a smooth stream; memory is bounded; two identical feeds
    summarize identically (no RNG anywhere)."""
    from ddl_tpu.obs.serving import TDigest

    rng = np.random.default_rng(2)
    stream = [float(x) for x in rng.lognormal(0.0, 0.5, size=30000)]

    def feed():
        d = TDigest(compression=256, exact_max=4096)
        for x in stream:
            d.add(x)
        return d

    a, b = feed(), feed()
    for q in (0.5, 0.95, 0.99):
        exact = float(np.quantile(stream, q))
        assert a.quantile(q) == pytest.approx(exact, rel=0.05), q
        assert a.quantile(q) == b.quantile(q)
    assert len(a._means) < 2000  # bounded, not the 30k stream
    assert a.count == 30000
    assert a.min == pytest.approx(min(stream))
    assert a.max == pytest.approx(max(stream))


def test_tdigest_merge_and_state_roundtrip():
    """merge() of per-stream digests approximates the single-stream
    digest; a two-operand merge is symmetric and a fixed merge order is
    fully deterministic (what the fold's sorted-stream-name render
    relies on); state round-trips exactly, including the unmerged
    buffer (resume determinism)."""
    from ddl_tpu.obs.serving import TDigest

    rng = np.random.default_rng(3)
    xs = [float(x) for x in rng.normal(10.0, 2.0, size=9000)]

    parts = [TDigest() for _ in range(3)]
    for i, x in enumerate(xs):
        parts[i % 3].add(x)

    def chain(order):
        d = TDigest()
        for i in order:
            d.merge(parts[i])
        return d

    ab, ab2, ba = chain((0, 1, 2)), chain((0, 1, 2)), chain((2, 1, 0))
    assert ab.count == ba.count == len(xs)
    # single two-operand merge is symmetric (sorted combined points)
    xy = TDigest(); xy.merge(parts[0]); xy.merge(parts[1])
    yx = TDigest(); yx.merge(parts[1]); yx.merge(parts[0])
    assert xy.quantile(0.95) == yx.quantile(0.95)
    for q in (0.5, 0.95, 0.99):
        assert ab.quantile(q) == ab2.quantile(q)  # same order: identical
        exact = float(np.quantile(xs, q))
        assert ab.quantile(q) == pytest.approx(exact, rel=0.05)
        assert ba.quantile(q) == pytest.approx(exact, rel=0.05)

    # round-trip: partially-filled buffer preserved verbatim
    d = TDigest()
    for x in xs[:700]:
        d.add(x)
    rt = TDigest.from_state(json.loads(json.dumps(d.state_dict())))
    assert rt.state_dict() == d.state_dict()
    for x in xs[700:1400]:
        d.add(x)
        rt.add(x)
    assert rt.quantile(0.95) == d.quantile(0.95)


def test_tdigest_migrates_reservoir_state():
    """A reservoir-era (QuantileAccumulator) sidecar state loads
    transparently: distribution, count, total, min/max preserved."""
    from ddl_tpu.obs.serving import QuantileAccumulator, TDigest

    acc = QuantileAccumulator(capacity=64)
    xs = [float(x) for x in np.random.default_rng(4).uniform(0, 1, 50)]
    for x in xs:
        acc.add(x)
    dig = TDigest.from_state(acc.state_dict())
    assert dig.count == 50
    assert dig.mean == pytest.approx(acc.mean)
    for q in (0.5, 0.95, 0.99):
        assert dig.quantile(q) == pytest.approx(acc.quantile(q))

    # ServingStats.from_state with reservoir acc blocks (old sidecar)
    from ddl_tpu.obs.serving import ServingStats

    old = {
        "acc": {
            name: QuantileAccumulator(capacity=16).state_dict()
            for name in ("latency_s", "queue_delay_s", "ttft_s", "tok_per_s")
        },
        "requests": 3, "cold": 1, "tokens": 48, "prompt_tokens": 24,
        "spans": {"decode": [32, 1.0, 2.0]}, "chips": 2,
    }
    stats = ServingStats.from_state(old)
    assert stats.requests == 3 and stats.chips == 2
    assert stats.summary()["agg_tok_per_s"] == pytest.approx(32.0)


def test_serving_spans_are_per_engine_and_per_run():
    """Two decode smokes from different processes (same engine-less
    events, different run ids) minutes apart must not share one span —
    the multi-smoke CI stream regression (ROADMAP carry-over)."""
    from ddl_tpu.obs.serving import ServingStats

    def dec(ts, run, engine=None):
        return {
            "kind": "decode", "ts": ts, "run": run, "new_tokens": 8,
            "batch": 1, "dur": 0.2, "warm": True, "tok_per_s": 40.0,
            **({"engine": engine} if engine else {}),
        }

    events = [
        dec(10.0, "runA"), dec(10.2, "runA"),      # smoke 1: [9.8, 10.2]
        dec(310.0, "runB"), dec(310.2, "runB"),    # smoke 2, 5 min later
        dec(600.0, "runC", engine="serve"),
        dec(600.4, "runC", engine="serve"),
    ]
    s = ServingStats.from_events(events).summary()
    # 48 tokens over 0.4 + 0.4 + 0.6 seconds of ACTIVITY, not ~590s
    assert s["agg_tok_per_s"] == pytest.approx(48 / 1.4)


def test_incident_lists_bounded_with_running_totals(tmp_path):
    """The sidecar must stay bounded on a run with thousands of
    incidents: retained lists cap at MAX_EVENTS_PER_LIST, totals keep
    counting, renders say how many are shown — and warm stays
    byte-identical to cold through the truncation."""
    from ddl_tpu.obs.fold import MAX_EVENTS_PER_LIST, SIDECAR_NAME, fold_job
    from ddl_tpu.obs.report import summarize_from_fold

    job = "flood"
    n = MAX_EVENTS_PER_LIST + 300
    evs = [
        _ev(0, "anomaly", 10.0 + i, step=i, type="loss_spike", value=9.9)
        for i in range(n)
    ]
    evs.append(_ev(0, "period", 5000.0, step=0, period=0, steps=10,
                   elapsed=1.0, steps_per_sec=10.0, phases={"step": 1.0}))
    _append(tmp_path, job, 0, [json.dumps(e) for e in evs[: n // 2]])
    _render_both(tmp_path, job, cache=True)
    _append(tmp_path, job, 0, [json.dumps(e) for e in evs[n // 2:]])
    warm_s, warm_p, fold = _render_both(tmp_path, job, cache=True)
    cold_s, cold_p, _ = _render_both(tmp_path, job, cache=False)
    assert warm_s == cold_s and warm_p == cold_p
    s = summarize_from_fold(fold)
    assert s["counts"]["anomalies"] == n
    assert len(s["anomalies"]) == MAX_EVENTS_PER_LIST
    assert f"anomalies ({n}, last {MAX_EVENTS_PER_LIST} shown)" in warm_s
    # the sidecar holds the capped tail, not the flood
    sidecar = json.loads(
        (tmp_path / "by_job_id" / job / SIDECAR_NAME).read_text()
    )
    stream = sidecar["streams"]["events-h000.jsonl"]
    assert len(stream["anomalies"]) == MAX_EVENTS_PER_LIST
    assert stream["totals"]["anomalies"] == n
    # re-fold of nothing stays O(heads)
    _, _, fold3 = _render_both(tmp_path, job, cache=True)
    assert fold3.bytes_read <= 64


# ---------------------------------------------------------------------------
# restart_latency
# ---------------------------------------------------------------------------


def test_steptrace_emits_restart_latency_once(tmp_path, monkeypatch):
    import ddl_tpu.obs.steptrace as st_mod
    from ddl_tpu.obs import EventWriter, read_events
    from ddl_tpu.obs.steptrace import StepTrace

    import time as _time

    origin = _time.time() - 2.5
    monkeypatch.setenv("DDL_RELAUNCH_TS", repr(origin))
    monkeypatch.setattr(st_mod, "_relaunch_consumed", False)

    w = EventWriter(tmp_path, "rl", host=0)
    trace = StepTrace(w, emit_step_spans=0)
    trace.begin_period(0)
    for step in range(3):
        with trace.phase("data_wait", step=step):
            pass
        with trace.phase("step", step=step):
            pass
    trace.end_period(0, 0, elapsed=0.1, steps=3)
    w.close()

    events = read_events(tmp_path / "by_job_id" / "rl" / "events-h000.jsonl")
    rls = [e for e in events if e["kind"] == "restart_latency"]
    assert len(rls) == 1  # once, on the FIRST completed step
    assert rls[0]["step"] == 0
    assert rls[0]["latency"] == pytest.approx(2.5, abs=2.0)
    assert rls[0]["decision_ts"] == pytest.approx(origin)

    # a second StepTrace in the same process must NOT re-measure
    w2 = EventWriter(tmp_path, "rl", host=0)
    t2 = StepTrace(w2, emit_step_spans=0)
    with t2.phase("step", step=0):
        pass
    w2.close()
    events = read_events(tmp_path / "by_job_id" / "rl" / "events-h000.jsonl")
    assert len(
        [e for e in events if e["kind"] == "restart_latency"]
    ) == 1


def test_steptrace_failed_first_step_does_not_emit(tmp_path, monkeypatch):
    """A first step that RAISES must not consume the measurement: the
    restart didn't succeed, and a decision->crash latency would pollute
    the gate.  The next completed step owns it instead."""
    import time as _time

    import ddl_tpu.obs.steptrace as st_mod
    from ddl_tpu.obs import EventWriter, read_events
    from ddl_tpu.obs.steptrace import StepTrace

    monkeypatch.setenv("DDL_RELAUNCH_TS", repr(_time.time() - 1.0))
    monkeypatch.setattr(st_mod, "_relaunch_consumed", False)

    w = EventWriter(tmp_path, "rlf", host=0)
    trace = StepTrace(w, emit_step_spans=0)
    with pytest.raises(RuntimeError):
        with trace.phase("step", step=0):
            raise RuntimeError("mid-compile crash")
    events = read_events(
        tmp_path / "by_job_id" / "rlf" / "events-h000.jsonl"
    )
    assert not [e for e in events if e["kind"] == "restart_latency"]
    with trace.phase("step", step=1):
        pass
    w.close()
    events = read_events(
        tmp_path / "by_job_id" / "rlf" / "events-h000.jsonl"
    )
    rls = [e for e in events if e["kind"] == "restart_latency"]
    assert len(rls) == 1 and rls[0]["step"] == 1


def test_restart_latency_summarized_and_gated(tmp_path, capsys):
    """restart_latency flows into summarize and the diff gate: an
    inflated restart latency past --fail-slowdown FAILS; matching ones
    pass with the gate named on the OK line."""
    from ddl_tpu import cli

    def mk(job, latency):
        evs = [
            _ev(0, "period", 10.0 + p, step=p, period=p, steps=10,
                elapsed=1.0, steps_per_sec=10.0,
                phases={"step": 0.5}) for p in range(4)
        ]
        evs.append(_ev(
            0, "restart_latency", 20.0, step=5, latency=latency,
            decision_ts=15.0, repoch=1,
        ))
        _append(tmp_path, job, 0, [json.dumps(e) for e in evs])

    mk("rla", 2.0)
    mk("rlb", 2.1)
    mk("rlc", 9.0)

    cli.main(["obs", "summarize", "rla", "--log-dir", str(tmp_path)])
    assert "restart latency: 1 restart(s), last 2.0s" in (
        capsys.readouterr().out
    )

    cli.main([
        "obs", "diff", "rla", "rlb", "--log-dir", str(tmp_path),
        "--fail-slowdown", "0.5",
    ])
    out = capsys.readouterr().out
    assert "OK:" in out and "restart latency" in out

    with pytest.raises(SystemExit, match="restart latency"):
        cli.main([
            "obs", "diff", "rla", "rlc", "--log-dir", str(tmp_path),
            "--fail-slowdown", "0.5",
        ])


def test_pod_supervisor_stamps_relaunch_ts(tmp_path):
    """supervise_pod_command's spawn env: attempt 0 carries no
    DDL_RELAUNCH_TS (and strips an inherited one); after a restart the
    epoch record's decision stamp rides into the child env."""
    from ddl_tpu.supervisor import supervise_command

    seen = {}

    class FakeProc:
        def __init__(self, rc):
            self.rc = rc

        def poll(self):
            return self.rc

    calls = []

    def fake_call(argv, env=None):
        calls.append(dict(env))
        return 75 if len(calls) == 1 else 0

    import ddl_tpu.supervisor as sup_mod

    orig = sup_mod.subprocess.call
    sup_mod.subprocess.call = fake_call
    try:
        rc = supervise_command(
            ["prog"], max_restarts=2,
            env={"DDL_RELAUNCH_TS": "stale", "DDL_LOG_DIR": str(tmp_path)},
            sleep=lambda s: None, log=lambda m: None,
        )
    finally:
        sup_mod.subprocess.call = orig
    assert rc == 0
    assert "DDL_RELAUNCH_TS" not in calls[0]  # stale value stripped
    assert "DDL_RELAUNCH_TS" in calls[1]  # relaunch carries the decision
    float(calls[1]["DDL_RELAUNCH_TS"])  # parseable
    assert seen == {}
