"""Optimizer factory (train/state.py build_optimizer).

The reference runs torch's unconfigured Adam (``single.py:305``); the
factory adds the standard schedule surface (clipping, AdamW, warmup,
cosine) while keeping the default path — and therefore every existing
snapshot's opt-state tree — exactly plain Adam.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from ddl_tpu.train.state import build_optimizer


def _params():
    return {"w": jnp.ones((3,)), "b": jnp.zeros((2,))}


def _grads():
    return {"w": jnp.full((3,), 2.0), "b": jnp.full((2,), -1.0)}


def test_default_is_plain_adam():
    """Defaults must produce optax.adam's exact update and state tree (old
    snapshots depend on the structure)."""
    p, g = _params(), _grads()
    tx = build_optimizer(1e-3)
    ref = optax.adam(1e-3)
    s0, r0 = tx.init(p), ref.init(p)
    assert jax.tree.structure(s0) == jax.tree.structure(r0)
    u, _ = tx.update(g, s0, p)
    ru, _ = ref.update(g, r0, p)
    np.testing.assert_allclose(
        np.asarray(u["w"]), np.asarray(ru["w"]), rtol=1e-7
    )


def test_clip_by_global_norm():
    p, g = _params(), _grads()
    tx = build_optimizer(1e-3, grad_clip_norm=0.1)
    # Adam normalises update magnitude at step 1, so check the *state*:
    # mu after a clipped step is the gradient rescaled to norm 0.1.
    s_clip = tx.update(g, tx.init(p), p)[1]
    mu = s_clip[1][0].mu["w"]  # (clip, (adam scale_by_adam, ...))
    gnorm = float(np.sqrt(np.sum(np.square(np.concatenate(
        [np.asarray(x).ravel() for x in jax.tree.leaves(g)])))))
    expected = (1 - 0.9) * 2.0 * (0.1 / gnorm)
    np.testing.assert_allclose(np.asarray(mu), expected, rtol=1e-5)


def test_weight_decay_is_decoupled():
    """AdamW shrinks params toward zero even with zero gradients."""
    p = _params()
    g = jax.tree.map(jnp.zeros_like, p)
    tx = build_optimizer(1e-2, weight_decay=0.1)
    u, _ = tx.update(g, tx.init(p), p)
    assert float(u["w"][0]) < 0  # decay pulls w=1 down
    assert float(u["b"][0]) == 0  # b=0 stays


def test_warmup_and_cosine_schedule():
    """LR ramps 0 -> peak over warmup then decays to ~0 at decay_steps."""
    p = _params()
    g = _grads()
    tx = build_optimizer(
        1e-2, lr_schedule="cosine", warmup_steps=10, decay_steps=100
    )
    state = tx.init(p)
    norms = []
    for _ in range(100):
        u, state = tx.update(g, state, p)
        norms.append(float(jnp.abs(u["w"][0])))
    assert norms[0] < norms[9] < norms[10] * 1.5  # ramping up
    assert norms[-1] < norms[50] < norms[15]  # decaying
    assert norms[-1] < 1e-3 * max(norms)  # ~0 at the end

    with pytest.raises(ValueError):
        build_optimizer(1e-2, lr_schedule="cosine")  # decay_steps required
    with pytest.raises(ValueError):
        build_optimizer(1e-2, lr_schedule="nope")


def test_constant_with_warmup():
    p, g = _params(), _grads()
    tx = build_optimizer(1e-2, warmup_steps=5)
    state = tx.init(p)
    norms = []
    for _ in range(10):
        u, state = tx.update(g, state, p)
        norms.append(float(jnp.abs(u["w"][0])))
    assert norms[0] < norms[4]  # ramp
    np.testing.assert_allclose(norms[6], norms[9], rtol=1e-3)  # flat after


# ---------------------------------------------------------------------------
# Fused Adam (train/fused_optim.py): optax.adam's exact math and state
# tree, computed as one fusible pass per leaf.
# ---------------------------------------------------------------------------


def test_fused_adam_matches_optax_step_by_step():
    """fused_apply over several steps is bit-compatible (to float
    tolerance) with optax.adam + apply_updates: same params, same moment
    trees, same count — and the state STRUCTURE is identical, so
    snapshots written by either restore into the other."""
    from ddl_tpu.train.fused_optim import fused_adam

    p = {"w": jnp.linspace(0.1, 1.0, 12).reshape(3, 4),
         "b": jnp.full((5,), 0.3)}
    ref, fus = optax.adam(1e-3), fused_adam(1e-3)
    s_r, s_f = ref.init(p), fus.init(p)
    assert jax.tree.structure(s_r) == jax.tree.structure(s_f)
    rng = np.random.default_rng(0)
    pr = pf = p
    for _ in range(5):
        g = jax.tree.map(
            lambda x: jnp.asarray(rng.normal(size=x.shape), jnp.float32), p
        )
        u, s_r = ref.update(g, s_r, pr)
        pr = optax.apply_updates(pr, u)
        pf, s_f = fus.fused_apply(g, s_f, pf)
    for a, b in zip(jax.tree.leaves(pr), jax.tree.leaves(pf)):
        np.testing.assert_allclose(a, b, atol=1e-7, rtol=1e-6)
    for a, b in zip(jax.tree.leaves(s_r), jax.tree.leaves(s_f)):
        np.testing.assert_allclose(a, b, atol=1e-7, rtol=1e-6)


def test_fused_adam_schedule_and_update_endpoint():
    """The optax `update` endpoint (used by scale_tx and the pipeline
    factories) with a warmup-cosine schedule tracks optax.adam exactly,
    including the schedule-count state element."""
    from ddl_tpu.train.fused_optim import fused_adam

    p = _params()
    sched = optax.warmup_cosine_decay_schedule(0.0, 1e-3, 3, 10)
    ref, fus = optax.adam(sched), fused_adam(sched)
    s_r, s_f = ref.init(p), fus.init(p)
    assert jax.tree.structure(s_r) == jax.tree.structure(s_f)
    rng = np.random.default_rng(1)
    pr = pf = p
    for _ in range(6):
        g = jax.tree.map(
            lambda x: jnp.asarray(rng.normal(size=x.shape), jnp.float32), p
        )
        u, s_r = ref.update(g, s_r, pr)
        pr = optax.apply_updates(pr, u)
        uf, s_f = fus.update(g, s_f, pf)
        pf = optax.apply_updates(pf, uf)
    for a, b in zip(jax.tree.leaves(pr), jax.tree.leaves(pf)):
        np.testing.assert_allclose(a, b, atol=1e-7, rtol=1e-6)
    for a, b in zip(jax.tree.leaves(s_r), jax.tree.leaves(s_f)):
        np.testing.assert_allclose(a, b, atol=1e-7, rtol=1e-6)


def test_build_optimizer_fused_routing():
    """fused=True returns the fused transformation only for plain-Adam
    configs; weight decay / clipping keep the optax chain (and thus no
    fused_apply).  The grace wrap (scale_tx) REBUILDS a fused Adam with
    the scale baked in — fused_apply (and any ZeRO placement) survives
    the grace window instead of silently falling back to the two-pass
    replicated path (round 16)."""
    from ddl_tpu.train.recovery import scale_tx

    fused = build_optimizer(1e-3, fused=True)
    assert hasattr(fused, "fused_apply")
    assert not hasattr(build_optimizer(1e-3), "fused_apply")
    assert not hasattr(
        build_optimizer(1e-3, fused=True, weight_decay=0.01), "fused_apply"
    )
    assert not hasattr(
        build_optimizer(1e-3, fused=True, grad_clip_norm=1.0), "fused_apply"
    )
    # a non-fused tx still takes the generic wrap (no fused_apply)
    assert not hasattr(scale_tx(optax.adam(1e-3), 0.5), "fused_apply")
    # the scaled rebuild works through BOTH endpoints
    p = _params()
    w = scale_tx(fused, 0.5)
    assert hasattr(w, "fused_apply")
    s = w.init(p)
    u_half, _ = w.update(_grads(), s, p)
    u_full, _ = fused.update(_grads(), s, p)
    np.testing.assert_allclose(
        np.asarray(u_half["w"]), 0.5 * np.asarray(u_full["w"]), rtol=1e-6
    )
    p_half, _ = w.fused_apply(_grads(), s, p)
    np.testing.assert_allclose(
        np.asarray(p_half["w"]),
        np.asarray(p["w"]) + np.asarray(u_half["w"]),
        rtol=1e-6,
    )
