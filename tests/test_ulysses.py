"""Ulysses all-to-all sequence parallelism vs full attention (exact parity)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh

from ddl_tpu.parallel.ulysses import make_ulysses_self_attention

B, T, H, D = 2, 32, 8, 8


def full_attention(q, k, v, causal):
    scores = np.einsum("bqhd,bkhd->bhqk", q, k) / np.sqrt(D)
    if causal:
        tq = np.arange(T)
        scores = np.where(tq[None, :] <= tq[:, None], scores, -np.inf)
    p = np.exp(scores - scores.max(-1, keepdims=True))
    p /= p.sum(-1, keepdims=True)
    return np.einsum("bhqk,bkhd->bqhd", p, v)


@pytest.fixture(scope="module")
def qkv():
    rng = np.random.default_rng(0)
    return tuple(
        rng.normal(size=(B, T, H, D)).astype(np.float32) for _ in range(3)
    )


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("n_dev", [2, 4, 8])
def test_ulysses_matches_full(qkv, causal, n_dev):
    q, k, v = qkv
    mesh = Mesh(np.array(jax.devices()[:n_dev]), ("seq",))
    fn = make_ulysses_self_attention(mesh, causal=causal)
    out = np.asarray(fn(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v)))
    want = full_attention(q, k, v, causal)
    np.testing.assert_allclose(out, want, atol=2e-5, rtol=1e-4)


def test_ulysses_differentiable_matches_dense_grad():
    rng = np.random.default_rng(1)
    q, k, v = (
        jnp.asarray(rng.normal(size=(1, 16, 4, 4)), jnp.float32) for _ in range(3)
    )
    mesh = Mesh(np.array(jax.devices()[:4]), ("seq",))
    fn = make_ulysses_self_attention(mesh, causal=True)
    g = jax.grad(lambda a, b, c: fn(a, b, c).sum())(q, k, v)
    assert g.shape == q.shape and bool(jnp.isfinite(g).all())

    def dense(a, b, c):
        scores = jnp.einsum("bqhd,bkhd->bhqk", a, b) / 2.0
        tq = jnp.arange(16)
        scores = jnp.where(tq[None, :] <= tq[:, None], scores, -jnp.inf)
        p = jax.nn.softmax(scores, axis=-1)
        return jnp.einsum("bhqk,bkhd->bqhd", p, c).sum()

    gd = jax.grad(dense)(q, k, v)
    np.testing.assert_allclose(np.asarray(g), np.asarray(gd), atol=2e-5, rtol=1e-4)


def test_ulysses_rejects_indivisible_heads():
    mesh = Mesh(np.array(jax.devices()[:4]), ("seq",))
    fn = make_ulysses_self_attention(mesh)
    rng = np.random.default_rng(2)
    q = jnp.asarray(rng.normal(size=(1, 16, 3, 4)), jnp.float32)  # 3 heads, 4 devs
    with pytest.raises(ValueError, match="divide"):
        fn(q, q, q)


def test_lm_ulysses_matches_dense_model():
    """attn_impl='ulysses' reproduces the dense-attention model exactly."""
    import optax

    from ddl_tpu.models.transformer import LMConfig
    from ddl_tpu.parallel.sharding import LMMeshSpec
    from ddl_tpu.train.lm_steps import make_lm_step_fns

    def run(attn_impl, spec):
        cfg = LMConfig(
            vocab_size=32, d_model=32, n_layers=2, n_heads=4, head_dim=8,
            d_ff=64, compute_dtype="float32", attn_impl=attn_impl, remat=False,
        )
        fns = make_lm_step_fns(
            cfg, spec, optax.adam(1e-3), jax.random.key(0), 4, 16
        )
        rng = np.random.default_rng(0)
        x = rng.integers(0, 32, (4, 17))
        state = fns.init_state()
        state, m = fns.train(state, jnp.asarray(x[:, :-1]), jnp.asarray(x[:, 1:]))
        return float(m["loss"])

    ref = run("dense", LMMeshSpec())
    uly = run("ulysses", LMMeshSpec(data=2, seq=2, model=2))
    np.testing.assert_allclose(ref, uly, atol=1e-4)


def test_ulysses_gqa_matches_repeated_kv():
    """Grouped K/V through the Ulysses all-to-all equals repeat-then-attend;
    the exchange moves only Hkv K/V heads."""
    rng = np.random.default_rng(9)
    hq, hkv = 8, 4
    q = jnp.asarray(rng.normal(size=(2, 32, hq, 8)), jnp.float32)
    k = jnp.asarray(rng.normal(size=(2, 32, hkv, 8)), jnp.float32)
    v = jnp.asarray(rng.normal(size=(2, 32, hkv, 8)), jnp.float32)
    mesh = Mesh(np.array(jax.devices()[:4]), ("seq",))
    fn = make_ulysses_self_attention(mesh, causal=True)
    grouped = np.asarray(fn(q, k, v))
    repeated = np.asarray(fn(q, jnp.repeat(k, 2, 2), jnp.repeat(v, 2, 2)))
    np.testing.assert_allclose(grouped, repeated, atol=2e-5, rtol=1e-4)
    gk = jax.grad(lambda b: fn(q, b, v).sum())(k)
    rk = jax.grad(lambda b: fn(q, jnp.repeat(b, 2, 2), v).sum())(k)
    np.testing.assert_allclose(np.asarray(gk), np.asarray(rk), atol=2e-5)


def test_ulysses_gqa_flash_matches_dense():
    """Flash inner core under Ulysses with grouped K/V."""
    from ddl_tpu.ops.attention import dense_attention
    from ddl_tpu.ops.flash_attention import flash_attention

    rng = np.random.default_rng(10)
    q = jnp.asarray(rng.normal(size=(1, 64, 8, 8)), jnp.float32)
    k = jnp.asarray(rng.normal(size=(1, 64, 4, 8)), jnp.float32)
    v = jnp.asarray(rng.normal(size=(1, 64, 4, 8)), jnp.float32)
    mesh = Mesh(np.array(jax.devices()[:2]), ("seq",))
    fn = make_ulysses_self_attention(mesh, causal=True, attn_fn=flash_attention)
    out = np.asarray(fn(q, k, v))
    want = np.asarray(dense_attention(q, k, v, causal=True))
    np.testing.assert_allclose(out, want, atol=2e-5, rtol=1e-4)


def test_ulysses_gqa_rejects_unsplittable_kv():
    """Hkv must divide by the seq axis (the K/V all-to-all keeps whole
    groups aligned); the clear error fires at trace time."""
    rng = np.random.default_rng(11)
    q = jnp.asarray(rng.normal(size=(1, 32, 8, 8)), jnp.float32)
    k = jnp.asarray(rng.normal(size=(1, 32, 2, 8)), jnp.float32)
    mesh = Mesh(np.array(jax.devices()[:4]), ("seq",))
    fn = make_ulysses_self_attention(mesh, causal=True, jit=False)
    with pytest.raises(ValueError, match="K/V head count"):
        fn(q, k, k)
