"""Worker process for the multi-host integration tests (test_multihost.py).

Runs one of two cooperating processes (launcher env contract
``DDL_COORDINATOR``/``DDL_NUM_PROCESSES``/``DDL_PROCESS_ID`` —
``launch.bootstrap``; Gloo-backed ``jax.distributed.initialize`` on CPU;
4 simulated devices each -> one 8-device global mesh).  Two modes via
``DDL_TEST_MODE``:

* ``cnn`` (default) — the FULL CNN Trainer: per-process data sharding
  (``ShardedEpochSampler``), cross-process global-batch assembly
  (``shard_batch`` -> ``make_array_from_process_local_data``), and
  cross-process metric gathers (``_to_host`` -> ``process_allgather``).
* ``lm`` — the transformer family on a multi-host (data, pipe, model)
  mesh with FSDP and the 1F1B pipeline schedule, in two placement phases
  so both the data-axis collectives (FSDP all-gathers, DP gradient
  reduction) and the pipe-axis 1F1B ppermutes cross the process boundary
  (see ``main_lm``).

Not collected by pytest (no ``test_`` prefix).
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from ddl_tpu.launch import bootstrap, force_cpu_devices, world_info  # noqa: E402

force_cpu_devices(4)

import jax  # noqa: E402

from ddl_tpu.config import preset  # noqa: E402
from ddl_tpu.train import Trainer  # noqa: E402


def checksum_params(params) -> str:
    """sha256 over the GLOBAL value of every leaf (gathered to every
    process), so two processes agreeing means the sharded state agrees."""
    import hashlib

    import numpy as np

    from ddl_tpu.train.trainer import _to_host

    h = hashlib.sha256()
    for leaf in jax.tree.leaves(params):
        h.update(np.ascontiguousarray(_to_host(leaf)).tobytes())
    return h.hexdigest()


def main_lm(info) -> None:
    """Two phases over a (data=2, pipe=2, model=2) mesh, differing only in
    which mesh axis spans the two processes (with 8 process-major devices
    exactly one 2-sized axis can cross the boundary):

    * phase A — default device order: ``data`` is outermost, so the DP
      gradient reduction and the FSDP all-gather/reduce-scatter cross the
      process boundary; pipe/model stay intra-process.
    * phase B — devices permuted so ``pipe`` carries the process bit: the
      1F1B stage-handoff ``ppermute``s (and cotangent reverse hops) cross
      the boundary — the DCN-placement analog of the reference's
      inter-node pipeline edge.  TP all-reduces remain intra-process in
      both phases, the realistic placement for a model axis.
    """
    import numpy as np
    import optax

    from ddl_tpu.models.transformer import LMConfig
    from ddl_tpu.parallel.sharding import LMMeshSpec
    from ddl_tpu.train.lm_steps import make_lm_step_fns

    B, T = 8, 16
    cfg = LMConfig(
        vocab_size=32, d_model=32, n_layers=4, n_heads=4, head_dim=8,
        d_ff=64, compute_dtype="float32", remat=True, fsdp=True,
    )
    spec = LMMeshSpec(data=2, model=2, pipe=2)
    all_devs = jax.devices()
    # build_lm_mesh reshapes the device list as (data, pipe, seq, expert,
    # model), flat index d*4 + p*2 + m.  Handing it device id p*4 + d*2 + m
    # at that position puts the process bit (id >= 4) on the pipe axis.
    pipe_cross = [
        all_devs[p * 4 + d * 2 + m]
        for d in (0, 1) for p in (0, 1) for m in (0, 1)
    ]
    sums = []
    for devices in (None, pipe_cross):
        fns = make_lm_step_fns(
            cfg, spec, optax.adam(1e-2), jax.random.key(0), B, T,
            num_microbatches=2, pipeline_schedule="1f1b", devices=devices,
        )
        tok_sharding = jax.sharding.NamedSharding(
            fns.mesh, jax.sharding.PartitionSpec("data", "seq")
        )

        def globalize(arr):
            # both processes draw the same global batch (same seed); each
            # contributes the shards it addresses
            return jax.make_array_from_callback(
                arr.shape, tok_sharding, lambda idx: arr[idx]
            )

        state = fns.init_state()
        rng = np.random.default_rng(7)
        for _ in range(3):
            toks = rng.integers(0, 32, (B, T + 1))
            state, m = fns.train(
                state, globalize(toks[:, :-1]), globalize(toks[:, 1:])
            )
            assert np.isfinite(float(m["loss"])), m
        ev = fns.evaluate(
            state, globalize(toks[:, :-1]), globalize(toks[:, 1:])
        )
        assert np.isfinite(float(ev["loss"])), ev
        sums.append(checksum_params(state.params))
    # the two phases run the same math on the same data — placement must
    # not change the result, and both processes must agree
    assert sums[0] == sums[1], sums
    print(
        f"WORKER_OK process={info['process_index']} checksum={sums[0]}",
        flush=True,
    )


def main() -> None:
    bootstrap()  # reads DDL_COORDINATOR / DDL_NUM_PROCESSES / DDL_PROCESS_ID
    info = world_info()
    assert info["process_count"] == 2, info
    assert info["global_device_count"] == 8, info

    if os.environ.get("DDL_TEST_MODE") == "lm":
        main_lm(info)
        return

    cfg = preset(
        "dp",
        **{
            "mesh.data": "8",
            "data.image_size": "32",
            "data.global_batch_size": "16",
            "data.eval_batch_size": "16",
            "data.synthetic_num_train": "48",
            "data.synthetic_num_test": "16",
            "data.num_workers": "0",
            "model.growth_rate": "4",
            "model.block_config": "[2,2]",
            "model.num_init_features": "8",
            "model.bn_size": "2",
            "train.max_epochs": "2",
            "train.save_best_qwk": "false",
            "train.preemption_save": "false",
            "train.log_dir": os.environ["DDL_TEST_LOG_DIR"],
            # isolate from the developer's ./checkpoints: a stale snapshot
            # under the default dir + default job id would auto-resume a
            # mismatched config and fail the run
            "train.checkpoint_dir": os.path.join(
                os.environ["DDL_TEST_LOG_DIR"], "ckpt"
            ),
        },
    )
    trainer = Trainer(cfg)
    trainer.train()
    # Every process computed from the same global batches, so the final
    # state must agree bit-for-bit on its global value.
    print(
        f"WORKER_OK process={info['process_index']} "
        f"checksum={checksum_params(trainer.state.params)}",
        flush=True,
    )


if __name__ == "__main__":
    main()
