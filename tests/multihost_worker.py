"""Worker process for the multi-host integration test (test_multihost.py).

Runs the FULL CNN Trainer as one of two cooperating processes: the
launcher env contract (``DDL_COORDINATOR``/``DDL_NUM_PROCESSES``/
``DDL_PROCESS_ID`` — ``launch.bootstrap``), Gloo-backed
``jax.distributed.initialize`` on CPU, per-process data sharding
(``ShardedEpochSampler``), cross-process global-batch assembly
(``shard_batch`` -> ``make_array_from_process_local_data``), and
cross-process metric gathers (``_to_host`` -> ``process_allgather``).
Not collected by pytest (no ``test_`` prefix).
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from ddl_tpu.launch import bootstrap, force_cpu_devices, world_info  # noqa: E402

force_cpu_devices(4)

import jax  # noqa: E402

from ddl_tpu.config import preset  # noqa: E402
from ddl_tpu.train import Trainer  # noqa: E402


def main() -> None:
    bootstrap()  # reads DDL_COORDINATOR / DDL_NUM_PROCESSES / DDL_PROCESS_ID
    info = world_info()
    assert info["process_count"] == 2, info
    assert info["global_device_count"] == 8, info

    cfg = preset(
        "dp",
        **{
            "mesh.data": "8",
            "data.image_size": "32",
            "data.global_batch_size": "16",
            "data.eval_batch_size": "16",
            "data.synthetic_num_train": "48",
            "data.synthetic_num_test": "16",
            "data.num_workers": "0",
            "model.growth_rate": "4",
            "model.block_config": "[2,2]",
            "model.num_init_features": "8",
            "model.bn_size": "2",
            "train.max_epochs": "2",
            "train.save_best_qwk": "false",
            "train.preemption_save": "false",
            "train.log_dir": os.environ["DDL_TEST_LOG_DIR"],
        },
    )
    trainer = Trainer(cfg)
    trainer.train()
    # Every process computed from the same global batches, so the final
    # state must agree bit-for-bit; hash the raw bytes of every leaf (via
    # the multihost gather, so each process sees the full global arrays).
    import hashlib

    import numpy as np

    from ddl_tpu.train.trainer import _to_host

    h = hashlib.sha256()
    for leaf in jax.tree.leaves(trainer.state.params):
        h.update(np.ascontiguousarray(_to_host(leaf)).tobytes())
    print(f"WORKER_OK process={info['process_index']} checksum={h.hexdigest()}",
          flush=True)


if __name__ == "__main__":
    main()
