"""Checkpoint/resume for the transformer LM family, incl. elastic resharding.

The reference's DCP resume (``ddp.py:129-133``) restores onto the same
topology it saved from.  Orbax writes global arrays, so a snapshot saved on
one mesh restores onto a different mesh/sharding — tested here by saving
from a (data=2, model=2) run and resuming on (data=4, model=1).
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax

from ddl_tpu.checkpoint import load_snapshot, save_snapshot
from ddl_tpu.models.transformer import LMConfig
from ddl_tpu.parallel.sharding import LMMeshSpec
from ddl_tpu.train.lm_steps import make_lm_step_fns


def _cfg():
    return LMConfig(
        vocab_size=32, d_model=32, n_layers=2, n_heads=4, head_dim=8,
        d_ff=64, compute_dtype="float32", remat=False,
    )


def _fns(spec):
    return make_lm_step_fns(
        _cfg(), spec, optax.adam(1e-3), jax.random.key(0), 4, 16
    )


def _batches(n):
    rng = np.random.default_rng(0)
    out = []
    for _ in range(n):
        x = rng.integers(0, 32, (4, 17))
        out.append((jnp.asarray(x[:, :-1]), jnp.asarray(x[:, 1:])))
    return out


def _train(fns, state, batches):
    loss = None
    for inp, tgt in batches:
        state, m = fns.train(state, inp, tgt)
        loss = float(m["loss"])
    return state, loss


def test_lm_resume_matches_uninterrupted(tmp_path):
    batches = _batches(5)
    fns = _fns(LMMeshSpec(data=2, model=2))
    ref_state, ref_loss = _train(fns, fns.init_state(), batches)

    state, _ = _train(fns, fns.init_state(), batches[:3])
    save_snapshot(tmp_path, "job-a", 3, state)
    restored, next_epoch = load_snapshot(tmp_path, "job-a", 3, fns.init_state())
    assert next_epoch == 4
    resumed, resumed_loss = _train(fns, restored, batches[3:])

    np.testing.assert_allclose(ref_loss, resumed_loss, atol=1e-5)
    assert int(resumed.step) == int(ref_state.step) == 5


def test_lm_restore_onto_different_mesh(tmp_path):
    batches = _batches(5)
    save_fns = _fns(LMMeshSpec(data=2, model=2))
    state, _ = _train(save_fns, save_fns.init_state(), batches[:3])
    save_snapshot(tmp_path, "job-b", 3, state)

    # resume on a different topology: 4-way data-parallel, no TP
    resume_fns = _fns(LMMeshSpec(data=4, model=1))
    restored, _ = load_snapshot(tmp_path, "job-b", 3, resume_fns.init_state())
    resharded, loss_resharded = _train(resume_fns, restored, batches[3:])

    # reference: uninterrupted on the original mesh
    ref_fns = _fns(LMMeshSpec(data=2, model=2))
    _, ref_loss = _train(ref_fns, ref_fns.init_state(), batches)

    np.testing.assert_allclose(ref_loss, loss_resharded, atol=1e-4)
    # params really live on the new mesh
    kernel = resharded.params["block0"]["mlp"]["wi"]["kernel"]
    assert kernel.sharding.mesh.shape["data"] == 4


def test_legacy_head_orientation_migrates_on_load(tmp_path):
    """Round 4 transposed the stored lm_head kernel to vocab-major
    (models/transformer.LMHead).  A snapshot saved with the old
    (d_model, vocab) orientation — kernel AND its param-shaped Adam
    moments — must restore via the transpose-on-load migration, so
    auto-resume across the upgrade continues instead of crashing."""
    import dataclasses

    cfg = dataclasses.replace(_cfg(), vocab_size=48)  # non-square head
    fns = make_lm_step_fns(
        cfg, LMMeshSpec(), optax.adam(1e-3), jax.random.key(0), 4, 16
    )
    state = fns.init_state()
    for inp, tgt in _batches(3):
        state, _ = fns.train(state, inp, tgt)

    def t_head(kp, leaf):
        keys = [getattr(k, "key", getattr(k, "name", k)) for k in kp]
        if "lm_head" in keys and keys[-1] == "kernel":
            return jnp.transpose(leaf)
        return leaf

    legacy = jax.tree_util.tree_map_with_path(t_head, state)
    # sanity: the legacy tree really is transposed where it matters
    changed = sum(
        int(a.shape != b.shape)
        for a, b in zip(jax.tree.leaves(legacy), jax.tree.leaves(state))
    )
    assert changed >= 3  # param + two Adam moments
    _save_legacy(tmp_path, "legacy", 0, legacy)

    restored, epochs = load_snapshot(tmp_path, "legacy", 0, state)
    assert epochs == 1
    for a, b in zip(jax.tree.leaves(restored), jax.tree.leaves(state)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    # non-legacy snapshots take the fast path and still round-trip —
    # and carry the explicit format field, so no shape sniffing runs
    save_snapshot(tmp_path, "new", 0, state)
    from ddl_tpu.checkpoint import snapshot_metadata

    assert "format" in snapshot_metadata(tmp_path, "new", 0)
    restored2, _ = load_snapshot(tmp_path, "new", 0, state)
    for a, b in zip(jax.tree.leaves(restored2), jax.tree.leaves(state)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    # a snapshot from a NEWER writer (format > ours) restores with a loud
    # warning instead of silently assuming the current layout
    import warnings

    import orbax.checkpoint as ocp

    from ddl_tpu.checkpoint import snapshot_path

    fpath = snapshot_path(tmp_path, "future", 0)
    fpath.parent.mkdir(parents=True, exist_ok=True)
    with ocp.StandardCheckpointer() as ckptr:
        ckptr.save(
            fpath, {"state": state, "epoch": 0, "format": 99}, force=True
        )
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        load_snapshot(tmp_path, "future", 0, state)
    assert any("newer than" in str(x.message) for x in w)


def _save_legacy(checkpoint_dir, job_id, epoch, state):
    """Write a pre-round-5 snapshot: the {state, epoch} tree WITHOUT the
    format field (what save_snapshot produced before the marker)."""
    import orbax.checkpoint as ocp

    from ddl_tpu.checkpoint import snapshot_path

    path = snapshot_path(checkpoint_dir, job_id, epoch)
    path.parent.mkdir(parents=True, exist_ok=True)
    with ocp.StandardCheckpointer() as ckptr:
        ckptr.save(path, {"state": state, "epoch": epoch}, force=True)


def test_legacy_square_head_warns(tmp_path):
    """A LEGACY (format-less) snapshot with a square lm_head kernel is
    orientation-ambiguous by shape: it restores as-is, loudly."""
    import warnings

    cfg = _cfg()  # d_model == 32; make vocab match for a square head
    import dataclasses

    cfg = dataclasses.replace(cfg, vocab_size=32)
    fns = make_lm_step_fns(
        cfg, LMMeshSpec(), optax.adam(1e-3), jax.random.key(0), 4, 16
    )
    state = fns.init_state()
    _save_legacy(tmp_path, "sq", 0, state)
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        restored, _ = load_snapshot(tmp_path, "sq", 0, state)
    assert any("SQUARE lm_head" in str(x.message) for x in w)
    for a, b in zip(jax.tree.leaves(restored), jax.tree.leaves(state)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    # the same square head saved with the format field restores silently
    save_snapshot(tmp_path, "sq_new", 0, state)
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        load_snapshot(tmp_path, "sq_new", 0, state)
    assert not any("SQUARE lm_head" in str(x.message) for x in w)


def test_load_params_honors_format_field(tmp_path):
    """``load_params`` (the decode tools' params-only restore) applies
    the same format handling as ``load_snapshot`` (ADVICE round 5): a
    legacy format-less snapshot gets the lm_head orientation migration,
    a newer-writer snapshot warns, and the restore skeleton is just the
    params subtree."""
    import dataclasses
    import warnings

    import orbax.checkpoint as ocp

    from ddl_tpu.checkpoint import load_params, save_snapshot, snapshot_path

    cfg = dataclasses.replace(_cfg(), vocab_size=48)  # non-square head
    fns = make_lm_step_fns(
        cfg, LMMeshSpec(), optax.adam(1e-3), jax.random.key(0), 4, 16
    )
    state = fns.init_state()

    # modern snapshot: params round-trip exactly, params subtree only
    save_snapshot(tmp_path, "modern", 0, state)
    params = load_params(tmp_path, "modern", 0)
    for a, b in zip(jax.tree.leaves(params), jax.tree.leaves(state.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    # legacy snapshot (no format field, head saved (d_model, vocab)):
    # with the caller's vocab_size the kernel migrates back to
    # vocab-major on load
    def t_head(kp, leaf):
        keys = [getattr(k, "key", getattr(k, "name", k)) for k in kp]
        if "lm_head" in keys and keys[-1] == "kernel":
            return jnp.transpose(leaf)
        return leaf

    legacy = jax.tree_util.tree_map_with_path(t_head, state)
    _save_legacy(tmp_path, "legacy-lp", 0, legacy)
    params = load_params(tmp_path, "legacy-lp", 0, vocab_size=48)
    for a, b in zip(jax.tree.leaves(params), jax.tree.leaves(state.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    # a format-less snapshot that is ALREADY vocab-major (written after
    # the layout change but before the marker) must NOT be transposed
    _save_legacy(tmp_path, "legacy-vm", 0, state)
    params = load_params(tmp_path, "legacy-vm", 0, vocab_size=48)
    for a, b in zip(jax.tree.leaves(params), jax.tree.leaves(state.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    # without vocab_size the orientation is unverifiable: restore
    # as-saved, loudly
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        params = load_params(tmp_path, "legacy-lp", 0)
    assert any("orientation unverified" in str(x.message) for x in w)

    # newer-writer snapshot: loud warning, not silent misinterpretation
    fpath = snapshot_path(tmp_path, "future-lp", 0)
    fpath.parent.mkdir(parents=True, exist_ok=True)
    with ocp.StandardCheckpointer() as ckptr:
        ckptr.save(
            fpath, {"state": state, "epoch": 0, "format": 99}, force=True
        )
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        load_params(tmp_path, "future-lp", 0)
    assert any("newer than" in str(x.message) for x in w)
