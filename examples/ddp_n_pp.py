"""Hybrid DP x PP on a (3,2) (data,pipe) mesh — the reference ``ddp_n_pp.py``
config (the north-star composition).

Equivalent to: ``python -m ddl_tpu.cli --preset dp_pp``
"""

import sys

from ddl_tpu.cli import main

if __name__ == "__main__":
    main(["--preset", "dp_pp", *sys.argv[1:]])
