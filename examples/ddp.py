"""Data-parallel training — the reference ``ddp.py`` config.

Equivalent to: ``python -m ddl_tpu.cli --preset dp``
(mesh.data defaults to 2; per-replica batch 15 as in the reference).
"""

import sys

from ddl_tpu.cli import main

if __name__ == "__main__":
    main(["--preset", "dp", *sys.argv[1:]])
