"""Train the transformer LM family on synthetic byte sequences or a corpus.

Argparse shim over ``ddl_tpu.train.lm_trainer.LMTrainer`` (the shared
training loop: default-on CSV logging, NaN watchdog, SIGTERM
checkpoint-and-exit, profiler hook).  Demonstrates the sharding-rule-driven
strategy surface the CNN entry points cannot express
(models/transformer.py): tensor parallelism, ring-attention sequence
parallelism, MoE expert parallelism, and FSDP — all selected from the
command line as mesh axis sizes, no code changes.

    python examples/train_lm.py --data 2 --seq 2 --model 2 --steps 100
    python examples/train_lm.py --experts 4 --expert-axis 2 --fsdp
    python examples/train_lm.py --pipe 2 --model 2 --microbatches 4

On a dev box without TPUs, add --cpu-devices 8 to simulate the mesh.
"""

from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--data", type=int, default=1)
    ap.add_argument("--seq", type=int, default=1)
    ap.add_argument("--model", type=int, default=1)
    ap.add_argument("--expert-axis", type=int, default=1)
    ap.add_argument("--pipe", type=int, default=1,
                    help="pipeline stages over the decoder layers")
    ap.add_argument("--microbatches", type=int, default=0,
                    help="pipeline microbatches when --pipe > 1 (default: --pipe)")
    ap.add_argument("--pipeline-schedule", default="gpipe",
                    choices=["gpipe", "1f1b", "zb"],
                    help="pipeline schedule when --pipe > 1: gpipe (all "
                    "forwards then all backwards), 1f1b (interleaved, "
                    "O(pipe) stage-activation residency), or zb "
                    "(zero-bubble: 1f1b with the backward split into "
                    "B/W and weight grads deferred into the cooldown "
                    "ticks; needs --virtual-stages 1)")
    ap.add_argument("--virtual-stages", type=int, default=1,
                    help="interleaved pipeline: layer chunks per device "
                    "(>1 shrinks the bubble by that factor; composes with "
                    "either --pipeline-schedule; needs layers %% (pipe*V) "
                    "== 0 and microbatches %% pipe == 0)")
    ap.add_argument("--accum", type=int, default=1,
                    help="gradient-accumulation chunks per step (pipe=1 only)")
    ap.add_argument("--dropout", type=float, default=0.0,
                    help="residual dropout rate")
    ap.add_argument("--experts", type=int, default=0, help="0 = dense MLP")
    ap.add_argument("--capacity-factor", type=float, default=1.5,
                    help="MoE warm-up expert capacity (see LMConfig)")
    ap.add_argument("--capacity-factor-min", type=float, default=1.0,
                    help="post-warm-up capacity the trainer anneals to "
                    "once the live router drop fraction converges "
                    "(= --capacity-factor disables the anneal)")
    ap.add_argument("--capacity-anneal-step", type=int, default=0,
                    help="anneal at this step regardless of the metric "
                    "(pipelined MoE runs, whose metrics lack drop_frac)")
    ap.add_argument("--moe-ep", default="auto",
                    choices=["auto", "gspmd", "alltoall"],
                    help="expert-parallel exchange: manual lax.all_to_all "
                    "dispatch or GSPMD-inserted collectives")
    ap.add_argument("--fsdp", action="store_true")
    ap.add_argument("--attn", default=None, choices=["dense", "ring", "ulysses"],
                    help="attention impl (default: ring when --seq > 1, else dense)")
    ap.add_argument("--flash", nargs="?", const="on", default="off",
                    choices=["on", "off", "auto"],
                    help="Pallas flash-attention kernel (dense/ulysses): "
                    "'--flash' / '--flash on' forces it, '--flash auto' "
                    "picks per run from the measured seq-len crossover")
    # validated against models.transformer.REMAT_POLICIES after parsing —
    # heavy imports stay deferred until --cpu-devices is handled
    ap.add_argument("--remat-policy", default="full",
                    help="per-block checkpoint policy (speed/HBM dial; "
                    "'dots' keeps matmul outputs, ~6%% faster backward)")
    ap.add_argument("--no-remat", action="store_true",
                    help="disable activation rematerialisation entirely "
                    "(fastest when the model fits in HBM; ~20%% over full "
                    "remat on one v5e chip)")
    ap.add_argument("--corpus", default=None,
                    help="token .npy or raw text file to train on "
                    "(default: synthetic Markov-chain bytes)")
    ap.add_argument("--eval-every", type=int, default=0,
                    help="with --corpus: evaluate held-out perplexity every "
                    "N steps (0 = off)")
    ap.add_argument("--eval-frac", type=float, default=0.05,
                    help="tail fraction of corpus windows held out for eval")
    ap.add_argument("--batch", type=int, default=16)
    ap.add_argument("--seq-len", type=int, default=256)
    ap.add_argument("--steps", type=int, default=100)
    ap.add_argument("--log-every", type=int, default=10,
                    help="console/CSV/obs period cadence in steps (1 = "
                    "per-step periods, the finest anomaly-detector feed)")
    ap.add_argument("--lr", type=float, default=3e-3)
    ap.add_argument("--warmup", type=int, default=0,
                    help="linear LR warmup steps")
    ap.add_argument("--cosine", action="store_true",
                    help="cosine-decay the LR to 0 over --steps")
    ap.add_argument("--weight-decay", type=float, default=0.0,
                    help=">0 switches to decoupled AdamW")
    ap.add_argument("--clip-norm", type=float, default=0.0,
                    help=">0 enables global-norm gradient clipping")
    ap.add_argument("--zero", action="store_true",
                    help="ZeRO-1 optimizer-state sharding over 'data': "
                    "moments + weight update on a 1/dp shard of every "
                    "large leaf (needs the fused Adam, so plain-Adam "
                    "configs only; flat step path)")
    ap.add_argument("--d-model", type=int, default=128)
    ap.add_argument("--layers", type=int, default=4)
    ap.add_argument("--kv-heads", type=int, default=0,
                    help="grouped-query attention: K/V head count "
                    "(0 = same as query heads; must divide the 8 query "
                    "heads — smaller K/V projections and decode cache)")
    ap.add_argument("--attn-window", type=int, default=0,
                    help="sliding-window attention: each position attends "
                    "only the last N positions (0 = full causal history)")
    ap.add_argument("--ce-chunk", type=int, default=0,
                    help="chunked head+CE fusion: sequence-chunk size for "
                    "the loss edge (0 = dense CE).  With a set chunk the "
                    "(B,T,V) logits never materialise — the big-vocab "
                    "memory lever; requires --seq 1")
    ap.add_argument("--cpu-devices", type=int, default=0,
                    help="simulate N CPU devices (dev/test)")
    ap.add_argument("--checkpoint-dir", default=None,
                    help="save a snapshot every --save-every steps (and on "
                    "held-out perplexity improvements / SIGTERM preemption)")
    ap.add_argument("--save-every", type=int, default=50)
    ap.add_argument("--keep-snapshots", type=int, default=0,
                    help="snapshot GC: keep only the newest K valid "
                    "snapshots (corrupt ones never count; 0 = keep all)")
    ap.add_argument("--resume-step", type=int, default=None,
                    help="restore the snapshot saved at this step (any mesh, "
                    "any pipeline layout — the saved layout is read from the "
                    "snapshot's metadata)")
    ap.add_argument("--fresh", action="store_true",
                    help="start from scratch even if this job id already "
                    "has snapshots (auto-resume is the default: a relaunch "
                    "with the same --job-id continues from the latest one)")
    ap.add_argument("--job-id", default="lm")
    ap.add_argument("--log-dir", default="training_logs",
                    help="MetricLogger CSV suite directory (loss, "
                    "tokens_per_sec, val_loss/val_ppl, epoch_time), "
                    "default-on so ddl_tpu.bench.analysis aggregates LM "
                    "runs alongside the CNN/ViT families; '' disables")
    ap.add_argument("--profile-dir", default=None,
                    help="capture a jax.profiler trace of one post-warmup "
                    "step window into this dir")
    ap.add_argument("--no-halt-on-nan", action="store_true",
                    help="keep training through non-finite losses")
    args = ap.parse_args()

    if args.cpu_devices:
        from ddl_tpu.launch import force_cpu_devices

        force_cpu_devices(args.cpu_devices)
    import jax

    from ddl_tpu.models.transformer import REMAT_POLICIES, LMConfig
    from ddl_tpu.parallel.sharding import LMMeshSpec
    from ddl_tpu.train.lm_trainer import LMRunConfig, LMTrainer
    from ddl_tpu.train.state import build_optimizer

    if args.remat_policy not in REMAT_POLICIES:
        ap.error(f"--remat-policy must be one of {REMAT_POLICIES}")

    flash = {"on": True, "off": False, "auto": "auto"}[args.flash]
    # Default attention core: ring when the sequence axis is sharded (the
    # tuned SP default), ulysses only when flash is *forced* (the kernel
    # cannot nest in ring).  flash=auto keeps the ring default — pass
    # --attn ulysses explicitly to let auto pick flash-ulysses under SP.
    cfg = LMConfig(
        vocab_size=256,
        d_model=args.d_model,
        n_layers=args.layers,
        n_heads=8,
        n_kv_heads=args.kv_heads,
        attn_window=args.attn_window,
        head_dim=args.d_model // 8,
        d_ff=4 * args.d_model,
        num_experts=args.experts,
        capacity_factor=args.capacity_factor,
        capacity_factor_min=args.capacity_factor_min,
        capacity_anneal_step=args.capacity_anneal_step,
        moe_ep=args.moe_ep,
        compute_dtype="bfloat16" if jax.default_backend() != "cpu" else "float32",
        attn_impl=args.attn
        or (("ulysses" if flash is True else "ring") if args.seq > 1 else "dense"),
        flash=flash,
        remat=not args.no_remat,
        remat_policy=args.remat_policy,
        fsdp=args.fsdp,
        dropout_rate=args.dropout,
        ce_chunk=args.ce_chunk,
    )
    spec = LMMeshSpec(
        args.data, args.seq, args.model, args.expert_axis, pipe=args.pipe
    )
    tx = build_optimizer(
        args.lr,
        weight_decay=args.weight_decay,
        grad_clip_norm=args.clip_norm,
        lr_schedule="cosine" if args.cosine else "constant",
        warmup_steps=args.warmup,
        decay_steps=args.steps if args.cosine else 0,
        # ZeRO's sharded update lives inside the fused per-leaf
        # expression (train/fused_optim); with_zero rejects optax chains
        fused=args.zero,
    )
    run = LMRunConfig(
        batch=args.batch,
        seq_len=args.seq_len,
        steps=args.steps,
        log_every=args.log_every,
        num_microbatches=args.microbatches,
        accum_steps=args.accum,
        pipeline_schedule=args.pipeline_schedule,
        virtual_stages=args.virtual_stages,
        zero_sharding=args.zero,
        corpus=args.corpus,
        eval_every=args.eval_every,
        eval_frac=args.eval_frac,
        checkpoint_dir=args.checkpoint_dir,
        save_every=args.save_every,
        keep_snapshots=args.keep_snapshots,
        resume_step=args.resume_step,
        auto_resume=not args.fresh,
        job_id=args.job_id,
        log_dir=args.log_dir or None,
        halt_on_nan=not args.no_halt_on_nan,
        profile_dir=args.profile_dir,
    )
    trainer = LMTrainer(cfg, spec, tx, run)
    print(f"mesh={spec} experts={args.experts} fsdp={args.fsdp}")
    trainer.train()


if __name__ == "__main__":
    main()
