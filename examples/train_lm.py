"""Train the transformer LM family on synthetic byte sequences.

Demonstrates the sharding-rule-driven strategy surface the CNN entry points
cannot express (models/transformer.py): tensor parallelism, ring-attention
sequence parallelism, MoE expert parallelism, and FSDP — all selected from
the command line as mesh axis sizes, no code changes.

    python examples/train_lm.py --data 2 --seq 2 --model 2 --steps 100
    python examples/train_lm.py --experts 4 --expert-axis 2 --fsdp
    python examples/train_lm.py --pipe 2 --model 2 --microbatches 4

On a dev box without TPUs, add --cpu-devices 8 to simulate the mesh.
"""

from __future__ import annotations

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--data", type=int, default=1)
    ap.add_argument("--seq", type=int, default=1)
    ap.add_argument("--model", type=int, default=1)
    ap.add_argument("--expert-axis", type=int, default=1)
    ap.add_argument("--pipe", type=int, default=1,
                    help="pipeline stages over the decoder layers")
    ap.add_argument("--microbatches", type=int, default=0,
                    help="pipeline microbatches when --pipe > 1 (default: --pipe)")
    ap.add_argument("--pipeline-schedule", default="gpipe",
                    choices=["gpipe", "1f1b"],
                    help="pipeline schedule when --pipe > 1: gpipe (all "
                    "forwards then all backwards) or 1f1b (interleaved, "
                    "O(pipe) stage-activation residency)")
    ap.add_argument("--virtual-stages", type=int, default=1,
                    help="interleaved pipeline: layer chunks per device "
                    "(>1 shrinks the bubble by that factor; composes with "
                    "either --pipeline-schedule; needs layers %% (pipe*V) "
                    "== 0 and microbatches %% pipe == 0)")
    ap.add_argument("--accum", type=int, default=1,
                    help="gradient-accumulation chunks per step (pipe=1 only)")
    ap.add_argument("--dropout", type=float, default=0.0,
                    help="residual dropout rate")
    ap.add_argument("--experts", type=int, default=0, help="0 = dense MLP")
    ap.add_argument("--fsdp", action="store_true")
    ap.add_argument("--attn", default=None, choices=["dense", "ring", "ulysses"],
                    help="attention impl (default: ring when --seq > 1, else dense)")
    ap.add_argument("--flash", nargs="?", const="on", default="off",
                    choices=["on", "off", "auto"],
                    help="Pallas flash-attention kernel (dense/ulysses): "
                    "'--flash' / '--flash on' forces it, '--flash auto' "
                    "picks per run from the measured seq-len crossover")
    # validated against models.transformer.REMAT_POLICIES after parsing —
    # heavy imports stay deferred until --cpu-devices is handled
    ap.add_argument("--remat-policy", default="full",
                    help="per-block checkpoint policy (speed/HBM dial; "
                    "'dots' keeps matmul outputs, ~6%% faster backward)")
    ap.add_argument("--no-remat", action="store_true",
                    help="disable activation rematerialisation entirely "
                    "(fastest when the model fits in HBM; ~20%% over full "
                    "remat on one v5e chip)")
    ap.add_argument("--corpus", default=None,
                    help="token .npy or raw text file to train on "
                    "(default: synthetic Markov-chain bytes)")
    ap.add_argument("--eval-every", type=int, default=0,
                    help="with --corpus: evaluate held-out perplexity every "
                    "N steps (0 = off)")
    ap.add_argument("--eval-frac", type=float, default=0.05,
                    help="tail fraction of corpus windows held out for eval")
    ap.add_argument("--batch", type=int, default=16)
    ap.add_argument("--seq-len", type=int, default=256)
    ap.add_argument("--steps", type=int, default=100)
    ap.add_argument("--lr", type=float, default=3e-3)
    ap.add_argument("--warmup", type=int, default=0,
                    help="linear LR warmup steps")
    ap.add_argument("--cosine", action="store_true",
                    help="cosine-decay the LR to 0 over --steps")
    ap.add_argument("--weight-decay", type=float, default=0.0,
                    help=">0 switches to decoupled AdamW")
    ap.add_argument("--clip-norm", type=float, default=0.0,
                    help=">0 enables global-norm gradient clipping")
    ap.add_argument("--d-model", type=int, default=128)
    ap.add_argument("--layers", type=int, default=4)
    ap.add_argument("--kv-heads", type=int, default=0,
                    help="grouped-query attention: K/V head count "
                    "(0 = same as query heads; must divide the 8 query "
                    "heads — smaller K/V projections and decode cache)")
    ap.add_argument("--attn-window", type=int, default=0,
                    help="sliding-window attention: each position attends "
                    "only the last N positions (0 = full causal history)")
    ap.add_argument("--cpu-devices", type=int, default=0,
                    help="simulate N CPU devices (dev/test)")
    ap.add_argument("--checkpoint-dir", default=None,
                    help="save a snapshot every --save-every steps")
    ap.add_argument("--save-every", type=int, default=50)
    ap.add_argument("--resume-step", type=int, default=None,
                    help="restore the snapshot saved at this step (any mesh, "
                    "any pipeline layout — the saved layout is read from the "
                    "snapshot's metadata)")
    ap.add_argument("--job-id", default="lm")
    ap.add_argument("--log-dir", default=None,
                    help="write the shared MetricLogger CSV suite (loss, "
                    "tokens_per_sec, val_loss/val_ppl, epoch_time) under "
                    "this dir so ddl_tpu.bench.analysis aggregates LM runs "
                    "alongside the CNN/ViT families")
    args = ap.parse_args()

    if args.cpu_devices:
        from ddl_tpu.launch import force_cpu_devices

        force_cpu_devices(args.cpu_devices)
    import jax
    import jax.numpy as jnp
    import numpy as np

    from ddl_tpu.models.transformer import REMAT_POLICIES, LMConfig
    from ddl_tpu.parallel.sharding import LMMeshSpec
    from ddl_tpu.train.lm_steps import make_lm_step_fns

    if args.remat_policy not in REMAT_POLICIES:
        ap.error(f"--remat-policy must be one of {REMAT_POLICIES}")

    flash = {"on": True, "off": False, "auto": "auto"}[args.flash]
    # Default attention core: ring when the sequence axis is sharded (the
    # tuned SP default), ulysses only when flash is *forced* (the kernel
    # cannot nest in ring).  flash=auto keeps the ring default — pass
    # --attn ulysses explicitly to let auto pick flash-ulysses under SP.
    cfg = LMConfig(
        vocab_size=256,
        d_model=args.d_model,
        n_layers=args.layers,
        n_heads=8,
        n_kv_heads=args.kv_heads,
        attn_window=args.attn_window,
        head_dim=args.d_model // 8,
        d_ff=4 * args.d_model,
        num_experts=args.experts,
        compute_dtype="bfloat16" if jax.default_backend() != "cpu" else "float32",
        attn_impl=args.attn
        or (("ulysses" if flash is True else "ring") if args.seq > 1 else "dense"),
        flash=flash,
        remat=not args.no_remat,
        remat_policy=args.remat_policy,
        fsdp=args.fsdp,
        dropout_rate=args.dropout,
    )
    spec = LMMeshSpec(
        args.data, args.seq, args.model, args.expert_axis, pipe=args.pipe
    )
    from ddl_tpu.train.state import build_optimizer

    tx = build_optimizer(
        args.lr,
        weight_decay=args.weight_decay,
        grad_clip_norm=args.clip_norm,
        lr_schedule="cosine" if args.cosine else "constant",
        warmup_steps=args.warmup,
        decay_steps=args.steps if args.cosine else 0,
    )
    fns = make_lm_step_fns(
        cfg, spec, tx, jax.random.key(0), args.batch, args.seq_len,
        num_microbatches=args.microbatches, accum_steps=args.accum,
        pipeline_schedule=args.pipeline_schedule,
        virtual_stages=args.virtual_stages,
    )
    print(f"mesh={spec} experts={args.experts} fsdp={args.fsdp}")

    logger = None
    if args.log_dir and jax.process_index() == 0:
        from ddl_tpu.utils import MetricLogger

        logger = MetricLogger(args.log_dir, args.job_id)

    if args.corpus:
        # real corpus: memmapped token windows, host-sharded per process;
        # each process loads 1/n_proc of the global batch and the shards
        # are assembled into one global jax.Array
        from ddl_tpu.data.lm_corpus import TokenBatches, TokenCorpus, encode_text_file

        n_proc, proc = jax.process_count(), jax.process_index()
        if args.batch % n_proc:
            raise ValueError(
                f"--batch {args.batch} must divide by process count {n_proc}"
            )
        path = args.corpus
        if not path.endswith(".npy"):
            npy = path + ".npy"
            stale = not os.path.exists(npy) or (
                os.path.getmtime(npy) < os.path.getmtime(path)
            )
            if stale and proc == 0:  # encode once, one writer
                encode_text_file(path, npy)
            if n_proc > 1:
                from jax.experimental import multihost_utils

                multihost_utils.sync_global_devices("corpus_encode")
            path = npy
        corpus = TokenCorpus(path, args.seq_len)
        if corpus.max_token() >= cfg.vocab_size:
            raise ValueError(
                f"corpus has token id {corpus.max_token()} but the model's "
                f"vocab_size is {cfg.vocab_size}; out-of-range ids would be "
                "silently clamped by the embedding gather"
            )
        eval_view = None
        if args.eval_every:
            train_view, ev = corpus.split(args.eval_frac)
            if len(ev) >= args.batch:
                eval_view = ev
            else:
                # too small to fill one batch: keep every window for training
                print(f"note: eval split ({len(ev)} windows) smaller than one "
                      f"batch of {args.batch}; held-out eval disabled — grow "
                      "--eval-frac or shrink --batch")
                train_view = corpus
        else:
            train_view = corpus
        batches = TokenBatches(
            train_view, args.batch // n_proc, n_proc, proc, seed=0
        )
        eval_batches = (
            TokenBatches(eval_view, args.batch // n_proc, n_proc, proc,
                         shuffle=False, seed=0)
            if eval_view is not None
            else None
        )
        print(f"corpus: {len(corpus)} windows of {args.seq_len}+1 tokens, "
              f"{len(batches)} train batches/epoch/host"
              + (f", {len(eval_batches)} eval batches" if eval_batches else ""))
        if n_proc > 1:
            from jax.sharding import NamedSharding, PartitionSpec as P

            gspec = NamedSharding(fns.mesh, P("data", "seq"))

        def sample_batch(step):
            # pure in step -> a resumed run continues the stream exactly
            inp, tgt = batches.batch_at(step)
            if n_proc > 1:  # host shards -> one global array
                return (
                    jax.make_array_from_process_local_data(gspec, inp),
                    jax.make_array_from_process_local_data(gspec, tgt),
                )
            return jnp.asarray(inp), jnp.asarray(tgt)
    else:
        # synthetic corpus: byte sequences from a fixed order-1 Markov
        # chain — learnable structure with a known entropy floor (shared
        # with generate_lm.py via ddl_tpu.data.synthetic_lm)
        from ddl_tpu.data.synthetic_lm import MarkovChain

        chain = MarkovChain()

        def sample_batch(step):
            # seeded by step so a resumed run continues the stream instead
            # of re-consuming batches the original run already trained on
            rng = np.random.default_rng(1000 + step)
            seqs = chain.sample(rng, args.batch, args.seq_len + 1)
            return jnp.asarray(seqs[:, :-1]), jnp.asarray(seqs[:, 1:])

    state = fns.init_state()
    start = 0
    if args.checkpoint_dir and args.resume_step is not None:
        from ddl_tpu.checkpoint import load_snapshot, snapshot_metadata
        from ddl_tpu.parallel.lm_pipeline import (
            saved_pipe_stages,
            saved_virtual_stages,
        )

        # The snapshot itself records its layout (pipe stages AND
        # interleaved virtual count) — no flag to get wrong.
        saved_md = snapshot_metadata(
            args.checkpoint_dir, args.job_id, args.resume_step
        )
        saved_pipe = saved_pipe_stages(saved_md["state"]["params"])
        saved_virtual = saved_virtual_stages(saved_md["state"]["params"])
        if saved_pipe == args.pipe and saved_virtual == args.virtual_stages:
            state, _ = load_snapshot(
                args.checkpoint_dir, args.job_id, args.resume_step, state
            )
            print("resumed (snapshots are mesh-independent)")
        else:
            # Cross-layout resume: the snapshot was written with a
            # different pipe stage count (possibly none).  Restore through
            # an abstract skeleton of the saved layout (no init, no step
            # functions — the saved run's batch/mesh/flash settings are
            # irrelevant to the state tree), then restructure params +
            # optimizer state and re-place onto this run's mesh.
            from ddl_tpu.parallel.lm_pipeline import (
                abstract_lm_state,
                convert_lm_state,
            )

            restored, _ = load_snapshot(
                args.checkpoint_dir, args.job_id, args.resume_step,
                abstract_lm_state(
                    cfg, tx, saved_pipe, mesh=fns.mesh, virtual=saved_virtual
                ),
            )
            if args.pipe > 1:
                if saved_pipe > 1:  # restage: merge, then re-split below
                    restored = convert_lm_state(restored)
                state = convert_lm_state(
                    restored, n_stages=args.pipe,
                    virtual=args.virtual_stages, like=state,
                )
            else:  # saved_pipe > 1 here (layouts differ): merge + place
                state = convert_lm_state(restored, like=state)
            print(
                f"resumed across layouts (saved pipe={saved_pipe} "
                f"virtual={saved_virtual} -> run pipe={args.pipe} "
                f"virtual={args.virtual_stages})"
            )
        start = int(state.step)
        print(f"continuing from step {start}")
    def eval_heldout(step):
        import math

        def to_global(x):
            # multi-host: assemble host shards into one global array, same
            # as the training batches
            if n_proc > 1:
                return jax.make_array_from_process_local_data(gspec, x)
            return jnp.asarray(x)

        ces = []
        for e_inp, e_tgt in eval_batches:
            em = fns.evaluate(state, to_global(e_inp), to_global(e_tgt))
            ces.append(float(em["ce"]))
        ce = float(np.mean(ces))
        print(f"  heldout: ce {ce:.4f} ppl {math.exp(ce):.2f} "
              f"({len(ces)} batches)")
        if logger is not None:
            logger.log("val_loss", ce, step)
            logger.log("val_ppl", math.exp(ce), step)

    t0 = time.perf_counter()
    t_window, window_start = t0, start
    for i in range(start, args.steps):
        inp, tgt = sample_batch(i)
        state, m = fns.train(state, inp, tgt)
        if i % 10 == 0 or i == args.steps - 1:
            print(
                f"step {i:4d} loss {float(m['loss']):.4f} "
                f"ce {float(m['ce']):.4f} moe_aux {float(m['moe_aux']):.4f}"
            )
            if logger is not None:
                logger.log("loss", float(m["loss"]), i)
                logger.log("ce", float(m["ce"]), i)
                now = time.perf_counter()
                if i > window_start:  # steady-state window rate
                    sps = (i - window_start) / (now - t_window)
                    logger.log("steps_per_sec", sps, i)
                    logger.log(
                        "tokens_per_sec", sps * args.batch * args.seq_len, i
                    )
                t_window, window_start = now, i
        aux_work = False
        if (args.corpus and args.eval_every and eval_batches
                and (i + 1) % args.eval_every == 0):
            eval_heldout(i)
            aux_work = True
        if args.checkpoint_dir and (i + 1) % args.save_every == 0:
            from ddl_tpu.checkpoint import save_snapshot

            save_snapshot(args.checkpoint_dir, args.job_id, i + 1, state)
            aux_work = True
        if aux_work:
            # keep eval/checkpoint walls out of the logged steady-state rate
            t_window, window_start = time.perf_counter(), i + 1
    steps_run = args.steps - start
    dt = time.perf_counter() - t0
    print(f"{steps_run} steps in {dt:.1f}s ({steps_run / dt:.2f} steps/s)")
    if logger is not None:
        # whole run as one "epoch" row so epoch_time_per_job covers LM jobs
        logger.log("epoch_time", dt, 0)


if __name__ == "__main__":
    main()
