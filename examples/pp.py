"""2-stage GPipe pipeline-parallel training — the reference ``pp.py`` config.

Equivalent to: ``python -m ddl_tpu.cli --preset pp``
"""

import sys

from ddl_tpu.cli import main

if __name__ == "__main__":
    main(["--preset", "pp", *sys.argv[1:]])
