"""Generate from a transformer-LM training snapshot (KV-cached decode).

Companion to train_lm.py: point it at the same --checkpoint-dir/--job-id
and the same model flags, and it decodes from the saved weights — any
snapshot layout (a pipeline-parallel run's snapshot is restructured to the
full layout automatically) and any mesh:

    python examples/train_lm.py --cpu-devices 8 --steps 200 \
        --checkpoint-dir /tmp/ck --save-every 100
    python examples/generate_lm.py --cpu-devices 8 --step 200 \
        --checkpoint-dir /tmp/ck --max-new 64

The reference has no generation path at all (its only inference surface is
the loss-less eval schedule, ``pp.py:146-150``); this is part of the
framework's beyond-parity LM family (``ddl_tpu/infer/decode.py``).
"""

from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--checkpoint-dir", required=True)
    ap.add_argument("--job-id", default="lm")
    ap.add_argument("--step", type=int, required=True,
                    help="snapshot step to load (any layout/mesh)")
    ap.add_argument("--data", type=int, default=1)
    ap.add_argument("--model", type=int, default=1,
                    help="tensor-parallel axis for decode")
    ap.add_argument("--d-model", type=int, default=128)
    ap.add_argument("--layers", type=int, default=4)
    ap.add_argument("--kv-heads", type=int, default=0,
                    help="must match the training run's --kv-heads (GQA)")
    ap.add_argument("--attn-window", type=int, default=0,
                    help="must match the training run's --attn-window "
                    "(sliding-window decode reads an O(window) cache slice)")
    ap.add_argument("--experts", type=int, default=0)
    ap.add_argument("--fsdp", action="store_true")
    ap.add_argument("--prompt-text", default=None,
                    help="byte-level text prompt (e.g. for --corpus-trained "
                    "models); output is decoded as text")
    ap.add_argument("--prompt-len", type=int, default=8)
    ap.add_argument("--max-new", type=int, default=64)
    ap.add_argument("--batch", type=int, default=2)
    ap.add_argument("--temperature", type=float, default=0.0,
                    help="0 = greedy")
    ap.add_argument("--top-k", type=int, default=None,
                    help="restrict sampling to the k most likely tokens")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--int8", default="none", choices=["none", "kv", "kv+w"],
                    help="int8 serving quantization (ops/quant.py): 'kv' "
                    "stores the KV cache int8 (+per-token scales), 'kv+w' "
                    "also streams weight-only int8 matmul kernels — the "
                    "HBM-traffic levers for the bandwidth-bound decode")
    ap.add_argument("--obs-log-dir", default=None,
                    help="emit per-request decode telemetry (lengths, "
                    "latency, queue delay, TTFT, tokens/s; dispatch/wait "
                    "spans) into this log dir's event stream; inspect "
                    "with `ddl_tpu obs summarize` (p50/p95/p99 table)")
    ap.add_argument("--requests", type=int, default=1,
                    help="decode the prompt batch this many times (the "
                    "first request pays the XLA compile and is flagged "
                    "cold; >= 4 gives the obs percentiles a warm sample)")
    ap.add_argument("--cpu-devices", type=int, default=0)
    args = ap.parse_args()

    if args.cpu_devices:
        from ddl_tpu.launch import force_cpu_devices

        force_cpu_devices(args.cpu_devices)
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    from ddl_tpu.checkpoint import load_snapshot, snapshot_metadata
    from ddl_tpu.infer import make_lm_generator
    from ddl_tpu.models.transformer import LMConfig
    from ddl_tpu.parallel.lm_pipeline import (
        abstract_lm_state,
        convert_lm_state,
        saved_pipe_stages,
        saved_virtual_stages,
    )
    from ddl_tpu.parallel.sharding import LMMeshSpec, build_lm_mesh

    cfg = LMConfig(
        vocab_size=256,
        d_model=args.d_model,
        n_layers=args.layers,
        n_heads=8,
        n_kv_heads=args.kv_heads,
        attn_window=args.attn_window,
        head_dim=args.d_model // 8,
        d_ff=4 * args.d_model,
        num_experts=args.experts,
        compute_dtype="bfloat16" if jax.default_backend() != "cpu" else "float32",
        fsdp=args.fsdp,
    )
    spec = LMMeshSpec(data=args.data, model=args.model)
    mesh = build_lm_mesh(spec)

    saved_md = snapshot_metadata(args.checkpoint_dir, args.job_id, args.step)
    saved_pipe = saved_pipe_stages(saved_md["state"]["params"])
    saved_virtual = saved_virtual_stages(saved_md["state"]["params"])
    # Adam's state structure is lr-independent, so any lr builds the right
    # restore skeleton; only params are used for decoding anyway.
    state, _ = load_snapshot(
        args.checkpoint_dir, args.job_id, args.step,
        abstract_lm_state(cfg, optax.adam(1e-3), saved_pipe, mesh=mesh,
                          virtual=saved_virtual),
    )
    if saved_pipe > 1:
        state = convert_lm_state(state)  # pipeline layout -> full
    print(f"loaded step {int(state.step)} (saved pipe={saved_pipe} "
          f"virtual={saved_virtual})")

    if args.int8 == "kv+w":
        from ddl_tpu.ops.quant import quantize_lm_params

        state = state.replace(params=quantize_lm_params(state.params))
    obs = None
    if args.obs_log_dir:
        from ddl_tpu.obs import EventWriter

        obs = EventWriter(args.obs_log_dir, args.job_id)
    gen = make_lm_generator(
        cfg,
        spec,
        prompt_len=args.prompt_len,
        max_new=args.max_new,
        batch=args.batch,
        temperature=args.temperature,
        top_k=args.top_k,
        mesh=mesh,
        kv_quant=args.int8 != "none",
        obs=obs,
    )

    if args.prompt_text is not None:
        enc = args.prompt_text.encode()
        if len(enc) > args.prompt_len:
            print(f"note: keeping the LAST {args.prompt_len} of "
                  f"{len(enc)} prompt bytes (raise --prompt-len to keep all)")
        raw = enc[-args.prompt_len:]  # trailing bytes = continuation context
        raw = raw.rjust(args.prompt_len, b" ")  # left-pad to the fixed shape
        prompts = np.tile(
            np.frombuffer(raw, np.uint8).astype(np.int32), (args.batch, 1)
        )
        toks = np.asarray(gen(state.params, jnp.asarray(prompts),
                              jax.random.key(args.seed)))
        for b in range(args.batch):
            text = bytes(int(t) % 256 for t in toks[b]).decode(errors="replace")
            print(f"{raw.decode(errors='replace')!r} -> {text!r}")
        return

    # default: prompts drawn from the synthetic training corpus's Markov
    # chain (the same seed-0 chain train_lm.py trains on,
    # ddl_tpu.data.synthetic_lm)
    from ddl_tpu.data.synthetic_lm import MarkovChain

    chain = MarkovChain()
    prompts = chain.sample(
        np.random.default_rng(args.seed), args.batch, args.prompt_len
    )

    from time import perf_counter

    for _ in range(max(0, args.requests - 1)):
        # warm serving requests for the percentile accumulators; the
        # submit timestamp exercises the queue-delay field
        gen(state.params, jnp.asarray(prompts),
            jax.random.key(args.seed), submitted_at=perf_counter())
    toks = np.asarray(gen(state.params, jnp.asarray(prompts),
                          jax.random.key(args.seed)))
    # score the continuations under the true chain: fraction of steps that
    # follow a plausible (top-8) transition — random tokens score ~8/256
    follows = chain.on_chain_fraction(prompts, toks)
    for b in range(args.batch):
        print(f"prompt {prompts[b].tolist()} -> {toks[b].tolist()}")
    print(f"fraction of generated steps on a top-8 chain transition: "
          f"{follows:.3f} (random would be ~{8 / 256:.3f})")


if __name__ == "__main__":
    main()
