"""Example entry points (importable for tests; each script is also directly
runnable: ``python examples/train_lm.py ...``)."""
