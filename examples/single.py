"""Single-device training — the reference ``single.py`` config.

Equivalent to: ``python -m ddl_tpu.cli --preset single``
"""

from ddl_tpu.cli import main

if __name__ == "__main__":
    main(["--preset", "single"])
