"""Serve a transformer-LM training snapshot with continuous batching.

Companion to train_lm.py / generate_lm.py: point it at the same
--checkpoint-dir/--job-id and model flags, and the continuous-batching
engine (``ddl_tpu/serve/``) serves the saved weights to N concurrent
synthetic clients — paged KV pool, bucketed prefill, admission control —
and renders the serving percentile report (p50/p95/p99 latency / queue
delay / TTFT / tokens-per-s, aggregate tokens/s/chip):

    python examples/train_lm.py --cpu-devices 8 --steps 200 \
        --checkpoint-dir /tmp/ck --save-every 100
    python examples/serve_lm.py --cpu-devices 1 --checkpoint-dir /tmp/ck \
        --job-id lm --step 200 --clients 16 --prompt-len 8:24 \
        --max-new 32:64

Where generate_lm.py decodes ONE fused batch per invocation (the
one-request-at-a-time baseline), this drives the serving loop: prompts
are admitted into the in-flight decode batch as lanes free up, finished
sequences retire and recycle their KV blocks, and overload is shed at
the front door.  `--compare-sequential` reports the throughput ratio
against generate_lm.py-style sequential decodes at equal settings.

This is ``ddl_tpu serve-bench`` with a checkpoint required — all flags
are shared (see ``python -m ddl_tpu.cli serve-bench --help``).
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> None:
    from ddl_tpu.serve.bench import main as bench_main

    argv = sys.argv[1:]
    if "--checkpoint-dir" not in argv and "--help" not in argv:
        raise SystemExit(
            "serve_lm.py serves a training snapshot: --checkpoint-dir "
            "(and --step) are required.  For random-init smoke mode use "
            "`python -m ddl_tpu.cli serve-bench` directly."
        )
    bench_main(argv)


if __name__ == "__main__":
    main()
