"""Train the ViT family on the APTOS-shape image data path.

Argparse shim over ``ddl_tpu.train.vit_trainer.ViTTrainer`` (the shared
training loop: default-on CSV logging, NaN watchdog, QWK-gated snapshots,
SIGTERM checkpoint-and-exit, profiler hook).  Second vision model family
(models/vit.py): the LM's transformer blocks run bidirectionally over a
patch sequence, sharded TP over heads/MLP and DP over batch by the same
logical-axis rule table — where the reference supports exactly one vision
model (DenseNet121, single.py:297-299).

    python examples/train_vit.py --cpu-devices 8 --data 2 --model 2 \
        --image-size 32 --patch 8 --epochs 2

Uses the synthetic APTOS-shape dataset when DDL_DATASET_DIR is unset
(same fallback as the CNN trainer); point it at the real data for the
full 224px task.
"""

from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--data", type=int, default=1)
    ap.add_argument("--model", type=int, default=1)
    ap.add_argument("--pipe", type=int, default=1,
                    help="GPipe stages over the encoder blocks")
    ap.add_argument("--microbatches", type=int, default=0,
                    help="microbatches when --pipe > 1 (default: --pipe)")
    ap.add_argument("--pipeline-schedule", default="gpipe",
                    choices=["gpipe", "1f1b", "zb"],
                    help="pipeline schedule when --pipe > 1 (1f1b: "
                    "interleaved, O(pipe) stage-activation residency; "
                    "zb: zero-bubble B/W-split 1f1b, --virtual-stages 1)")
    ap.add_argument("--virtual-stages", type=int, default=1,
                    help="interleaved pipeline: layer chunks per device "
                    "(>1 shrinks the bubble by that factor)")
    ap.add_argument("--accum", type=int, default=1,
                    help="gradient-accumulation chunks per step (pipe=1 only)")
    ap.add_argument("--dropout", type=float, default=0.0,
                    help="residual dropout rate")
    ap.add_argument("--fsdp", action="store_true")
    ap.add_argument("--zero", action="store_true",
                    help="ZeRO-1 optimizer-state sharding over 'data'. "
                    "Switches to the plain fused Adam (drops this "
                    "example's default weight-decay/clip chain — the "
                    "sharded update lives in the fused per-leaf "
                    "expression); flat step path only")
    ap.add_argument("--batch", type=int, default=32)
    ap.add_argument("--epochs", type=int, default=3)
    ap.add_argument("--lr", type=float, default=3e-4)
    ap.add_argument("--image-size", type=int, default=224)
    ap.add_argument("--patch", type=int, default=16)
    ap.add_argument("--d-model", type=int, default=384)
    ap.add_argument("--layers", type=int, default=12)
    ap.add_argument("--kv-heads", type=int, default=0,
                    help="grouped-query attention K/V head count (0 = MHA)")
    ap.add_argument("--num-train", type=int, default=256,
                    help="synthetic train examples (when no real dataset)")
    ap.add_argument("--num-test", type=int, default=64)
    ap.add_argument("--cpu-devices", type=int, default=0)
    ap.add_argument("--checkpoint-dir", default="checkpoints",
                    help="QWK-gated / preemption snapshot dir ('' disables)")
    ap.add_argument("--keep-snapshots", type=int, default=0,
                    help="snapshot GC: keep only the newest K valid "
                    "snapshots (corrupt ones never count; 0 = keep all)")
    ap.add_argument("--resume-epoch", type=int, default=None,
                    help="restore the snapshot saved at this epoch")
    ap.add_argument("--fresh", action="store_true",
                    help="start from scratch even if this job id already "
                    "has snapshots (auto-resume is the default: a relaunch "
                    "with the same --job-id continues from the latest one)")
    ap.add_argument("--job-id", default="vit")
    ap.add_argument("--log-dir", default="training_logs",
                    help="MetricLogger CSV suite directory (loss, "
                    "img_per_sec, val_loss/val_accuracy/qwk, epoch_time), "
                    "default-on so ddl_tpu.bench.analysis aggregates ViT "
                    "runs alongside the CNN/LM families; '' disables")
    ap.add_argument("--profile-dir", default=None,
                    help="capture a jax.profiler trace of one post-warmup "
                    "epoch into this dir")
    ap.add_argument("--no-halt-on-nan", action="store_true",
                    help="keep training through non-finite losses")
    args = ap.parse_args()

    if args.cpu_devices:
        from ddl_tpu.launch import force_cpu_devices

        force_cpu_devices(args.cpu_devices)
    import jax

    from ddl_tpu.config import DataConfig
    from ddl_tpu.models.vit import ViTConfig
    from ddl_tpu.parallel.sharding import LMMeshSpec
    from ddl_tpu.train.state import build_optimizer
    from ddl_tpu.train.vit_trainer import ViTRunConfig, ViTTrainer

    cfg = ViTConfig(
        image_size=args.image_size,
        patch_size=args.patch,
        d_model=args.d_model,
        n_layers=args.layers,
        n_heads=max(2, args.d_model // 64),
        n_kv_heads=args.kv_heads,
        head_dim=64 if args.d_model >= 128 else args.d_model // 2,
        d_ff=4 * args.d_model,
        compute_dtype="bfloat16" if jax.default_backend() != "cpu" else "float32",
        fsdp=args.fsdp,
        dropout_rate=args.dropout,
    )
    spec = LMMeshSpec(data=args.data, model=args.model, pipe=args.pipe)
    tx = (
        build_optimizer(args.lr, fused=True)
        if args.zero
        else build_optimizer(args.lr, weight_decay=0.05, grad_clip_norm=1.0)
    )
    run = ViTRunConfig(
        batch=args.batch,
        epochs=args.epochs,
        num_microbatches=args.microbatches,
        accum_steps=args.accum,
        pipeline_schedule=args.pipeline_schedule,
        virtual_stages=args.virtual_stages,
        zero_sharding=args.zero,
        checkpoint_dir=args.checkpoint_dir or None,
        keep_snapshots=args.keep_snapshots,
        resume_epoch=args.resume_epoch,
        auto_resume=not args.fresh,
        job_id=args.job_id,
        log_dir=args.log_dir or None,
        halt_on_nan=not args.no_halt_on_nan,
        profile_dir=args.profile_dir,
    )
    dc = DataConfig(
        image_size=args.image_size,
        global_batch_size=args.batch,
        eval_batch_size=args.batch,
        synthetic_num_train=args.num_train,
        synthetic_num_test=args.num_test,
    )
    trainer = ViTTrainer(cfg, spec, tx, run, data=dc)
    print(f"mesh=(data={args.data}, model={args.model}, pipe={args.pipe}) "
          f"fsdp={args.fsdp} patches={cfg.num_patches}")
    trainer.train()


if __name__ == "__main__":
    main()
