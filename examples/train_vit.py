"""Train the ViT family on the APTOS-shape image data path.

Second vision model family (models/vit.py): the LM's transformer blocks
run bidirectionally over a patch sequence, sharded TP over heads/MLP and
DP over batch by the same logical-axis rule table — where the reference
supports exactly one vision model (DenseNet121, single.py:297-299).

    python examples/train_vit.py --cpu-devices 8 --data 2 --model 2 \
        --image-size 32 --patch 8 --epochs 2

Uses the synthetic APTOS-shape dataset when DDL_DATASET_DIR is unset
(same fallback as the CNN trainer); point it at the real data for the
full 224px task.
"""

from __future__ import annotations

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--data", type=int, default=1)
    ap.add_argument("--model", type=int, default=1)
    ap.add_argument("--pipe", type=int, default=1,
                    help="GPipe stages over the encoder blocks")
    ap.add_argument("--microbatches", type=int, default=0,
                    help="microbatches when --pipe > 1 (default: --pipe)")
    ap.add_argument("--pipeline-schedule", default="gpipe",
                    choices=["gpipe", "1f1b"],
                    help="pipeline schedule when --pipe > 1 (1f1b: "
                    "interleaved, O(pipe) stage-activation residency)")
    ap.add_argument("--virtual-stages", type=int, default=1,
                    help="interleaved pipeline: layer chunks per device "
                    "(>1 shrinks the bubble by that factor)")
    ap.add_argument("--accum", type=int, default=1,
                    help="gradient-accumulation chunks per step (pipe=1 only)")
    ap.add_argument("--dropout", type=float, default=0.0,
                    help="residual dropout rate")
    ap.add_argument("--fsdp", action="store_true")
    ap.add_argument("--batch", type=int, default=32)
    ap.add_argument("--epochs", type=int, default=3)
    ap.add_argument("--lr", type=float, default=3e-4)
    ap.add_argument("--image-size", type=int, default=224)
    ap.add_argument("--patch", type=int, default=16)
    ap.add_argument("--d-model", type=int, default=384)
    ap.add_argument("--layers", type=int, default=12)
    ap.add_argument("--kv-heads", type=int, default=0,
                    help="grouped-query attention K/V head count (0 = MHA)")
    ap.add_argument("--num-train", type=int, default=256,
                    help="synthetic train examples (when no real dataset)")
    ap.add_argument("--num-test", type=int, default=64)
    ap.add_argument("--cpu-devices", type=int, default=0)
    ap.add_argument("--job-id", default="vit")
    ap.add_argument("--log-dir", default=None,
                    help="write the shared MetricLogger CSV suite (loss, "
                    "img_per_sec, val_loss/val_accuracy/qwk, epoch_time) so "
                    "ddl_tpu.bench.analysis aggregates ViT runs alongside "
                    "the CNN/LM families")
    args = ap.parse_args()

    if args.cpu_devices:
        from ddl_tpu.launch import force_cpu_devices

        force_cpu_devices(args.cpu_devices)
    import jax
    import numpy as np

    from ddl_tpu.config import DataConfig
    from ddl_tpu.data import DataLoader, ShardedEpochSampler, build_datasets, shard_batch
    from ddl_tpu.models.vit import ViTConfig
    from ddl_tpu.parallel.sharding import LMMeshSpec
    from ddl_tpu.train.state import build_optimizer
    from ddl_tpu.train.vit_steps import make_vit_step_fns
    from ddl_tpu.utils.metrics import masked_classification_eval

    cfg = ViTConfig(
        image_size=args.image_size,
        patch_size=args.patch,
        d_model=args.d_model,
        n_layers=args.layers,
        n_heads=max(2, args.d_model // 64),
        n_kv_heads=args.kv_heads,
        head_dim=64 if args.d_model >= 128 else args.d_model // 2,
        d_ff=4 * args.d_model,
        compute_dtype="bfloat16" if jax.default_backend() != "cpu" else "float32",
        fsdp=args.fsdp,
        dropout_rate=args.dropout,
    )
    spec = LMMeshSpec(data=args.data, model=args.model, pipe=args.pipe)
    tx = build_optimizer(args.lr, weight_decay=0.05, grad_clip_norm=1.0)
    fns = make_vit_step_fns(cfg, spec, tx, jax.random.key(0), args.batch,
                            num_microbatches=args.microbatches,
                            accum_steps=args.accum,
                            pipeline_schedule=args.pipeline_schedule,
                            virtual_stages=args.virtual_stages)
    print(f"mesh=(data={args.data}, model={args.model}, pipe={args.pipe}) "
          f"fsdp={args.fsdp} patches={cfg.num_patches}")

    dc = DataConfig(
        image_size=args.image_size,
        global_batch_size=args.batch,
        eval_batch_size=args.batch,
        synthetic_num_train=args.num_train,
        synthetic_num_test=args.num_test,
    )
    train_ds, test_ds = build_datasets(dc)
    n_proc, proc = jax.process_count(), jax.process_index()
    train_loader = DataLoader(
        train_ds, args.batch // n_proc,
        sampler=ShardedEpochSampler(len(train_ds), n_proc, proc, seed=0),
    )
    # deterministic full-coverage eval: ordered, sentinel-padded to static
    # shapes, padded rows (label -1) masked out — same contract as the CNN
    # Trainer's eval loop
    test_loader = DataLoader(
        test_ds, args.batch // n_proc,
        sampler=ShardedEpochSampler(
            len(test_ds), n_proc, proc,
            shuffle=False, drop_last=False, pad_mode="sentinel", seed=1,
        ),
        drop_last=False, pad_last_batch=True,
    )

    logger = None
    if args.log_dir and proc == 0:
        from ddl_tpu.utils import MetricLogger

        logger = MetricLogger(args.log_dir, args.job_id)

    state = fns.init_state()
    for epoch in range(args.epochs):
        train_loader.set_epoch(epoch)
        t0 = time.perf_counter()
        losses, steps = [], 0
        for images, labels in train_loader:
            gi, gl = shard_batch(fns.mesh, images, labels)
            state, m = fns.train(state, gi, gl)
            losses.append(float(m["loss"]))
            steps += 1
        dt = time.perf_counter() - t0
        logits, targets = [], []
        for images, labels in test_loader:
            gi, gl = shard_batch(fns.mesh, images, labels)
            logits.append(np.asarray(fns.evaluate(state, gi)))
            targets.append(np.asarray(gl))
        mets = masked_classification_eval(
            np.concatenate(logits), np.concatenate(targets)
        )
        print(f"epoch {epoch}: loss {np.mean(losses):.4f} "
              f"({steps} steps, {dt:.1f}s, {steps / dt:.2f} steps/s) | "
              f"val_acc {mets['val_accuracy']:.4f} qwk {mets['qwk']:.4f}")
        if logger is not None:
            logger.log("loss", float(np.mean(losses)), epoch)
            logger.log("epoch_time", dt, epoch)
            logger.log("steps_per_sec", steps / dt, epoch)
            logger.log("img_per_sec", steps * args.batch / dt, epoch)
            logger.log_many(mets, epoch)


if __name__ == "__main__":
    main()
