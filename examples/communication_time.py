"""Communication microbenchmark — the reference ``communication_time.py``.

Equivalent to: ``python -m ddl_tpu.bench.comm``
"""

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from ddl_tpu.bench.comm import run_comm_bench

if __name__ == "__main__":
    print(json.dumps(run_comm_bench(), indent=2))
