# Builds the `ddl-tpu:latest` image the launcher manifests reference
# (ddl_tpu/launcher/tpu_pod.py JobSpec.image) — the analog of the
# reference's pytorch/pytorch base image (reference Dockerfile:1-8), but
# TPU-native: jax[tpu] brings libtpu; one container runs on every host of
# the pod slice (one process per host, jax.distributed.initialize).
FROM python:3.12-slim

# build toolchain for the native C++ loader core (ddl_tpu/native)
RUN apt-get update && apt-get install -y --no-install-recommends \
    g++ make && rm -rf /var/lib/apt/lists/*

WORKDIR /workspace
COPY requirements.txt .
# jax[tpu] pulls libtpu from the Google releases index
RUN pip install --no-cache-dir -r requirements.txt \
    -f https://storage.googleapis.com/jax-releases/libtpu_releases.html

COPY pyproject.toml README.md ./
COPY ddl_tpu ddl_tpu
COPY examples examples
COPY tests tests
COPY bench.py .
RUN pip install --no-cache-dir --no-deps -e .
# importing ddl_tpu.native auto-builds libddl_loader.so via its Makefile;
# the Python fallback path keeps the image usable if only this build fails
RUN python -c "import ddl_tpu.native" || true

ENTRYPOINT ["python", "-m", "ddl_tpu.cli"]
